"""ISSUE 16: the fused descent-in-scan kernel tier, the double-buffered
ring ingest, and the large-batch (``--batch-scale``) recipe.

The fused tier's contract is BYTE parity, not tolerance: the one-program
scan body (ops/pallas_fused_step.py) computes its loss tile and descent
tile with the literal ``loss_tile``/``count_tile`` functions the
separate-programs oracle runs, on identical inputs, with the identical
backward program — so fused-vs-oracle equality is structural and these
tests pin it end to end (kernel outputs, gradients, whole TrainState +
priority tree across multi-dispatch megastep runs, bf16 and ensemble
included). The ingest double buffer's contract is that staging is
INVISIBLE: stage()+flush() must be byte-identical to a plain flush(),
including under ring-wrap overwrites between stage and flush.

Fast tests keep the small-capacity shapes of tests/test_megastep.py;
the large-batch 400-step guard acceptance and the scaled-recipe solve
ride the slow tier.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.agent import D4PGConfig, create_train_state
from d4pg_tpu.config import TrainConfig, apply_batch_scale, apply_env_preset
from d4pg_tpu.models.critic import DistConfig
from d4pg_tpu.ops.categorical import make_support
from d4pg_tpu.ops.pallas_fused_step import fused_categorical_loss_descent
from d4pg_tpu.ops.pallas_projection import fused_categorical_loss
from d4pg_tpu.ops.pallas_tree import find_prefix_pallas
from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.device_ring import DeviceRingSync, device_ring_init
from d4pg_tpu.replay.source import RequestedCaps, composition_matrix, negotiate
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
from d4pg_tpu.runtime.megastep import (
    make_megastep_device_per,
    make_megastep_device_per_fused,
)


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


# ------------------------------------------------------ kernel-level parity
def _kernel_inputs(B=40, A=11, L=300, seed=0):
    r = np.random.default_rng(seed)
    q = jnp.asarray(r.normal(size=(B, A)).astype(np.float32))
    p = jax.nn.softmax(jnp.asarray(r.normal(size=(B, A)).astype(np.float32)))
    rew = jnp.asarray(r.uniform(-1, 0, B).astype(np.float32))
    disc = jnp.asarray(r.uniform(0, 0.99, B).astype(np.float32))
    leaves = jnp.asarray(r.uniform(0.1, 2.0, L).astype(np.float32))
    pre = jnp.asarray(
        r.uniform(0, float(np.sum(np.asarray(leaves))) * 0.999, B)
        .astype(np.float32)
    )
    return q, p, rew, disc, pre, leaves


class TestFusedStepKernel:
    SUP = make_support(-5.0, 5.0, 11)

    def test_byte_identical_to_separate_programs(self):
        """ce/overlap match fused_categorical_loss and the descent matches
        find_prefix_pallas — all to the BYTE (the fused kernel runs the
        same tile functions on the same operands)."""
        q, p, rew, disc, pre, leaves = _kernel_inputs()
        ce_f, ov_f, idx_f = fused_categorical_loss_descent(
            self.SUP, q, p, rew, disc, pre, leaves, interpret=True
        )
        ce_s, ov_s = fused_categorical_loss(
            self.SUP, q, p, rew, disc, interpret=True
        )
        idx_s = find_prefix_pallas(leaves, pre, interpret=True)
        assert np.asarray(ce_f).tobytes() == np.asarray(ce_s).tobytes()
        assert np.asarray(ov_f).tobytes() == np.asarray(ov_s).tobytes()
        np.testing.assert_array_equal(np.asarray(idx_f), np.asarray(idx_s))
        assert np.asarray(idx_f).dtype == np.int32

    def test_gradients_byte_identical(self):
        """Both tiers share _fused_loss_grad_kernel, so an IS-weighted
        loss gradient through either is the same bytes."""
        q, p, rew, disc, pre, leaves = _kernel_inputs(seed=1)
        w = jnp.asarray(
            np.random.default_rng(2).uniform(0.2, 1.0, q.shape[0])
            .astype(np.float32)
        )

        def loss_fused(qq):
            ce, ov, _idx = fused_categorical_loss_descent(
                self.SUP, qq, p, rew, disc, pre, leaves, interpret=True
            )
            return jnp.sum(ce * w) + 0.5 * jnp.sum(ov * w)

        def loss_sep(qq):
            ce, ov = fused_categorical_loss(
                self.SUP, qq, p, rew, disc, interpret=True
            )
            return jnp.sum(ce * w) + 0.5 * jnp.sum(ov * w)

        gf = np.asarray(jax.grad(loss_fused)(q))
        gs = np.asarray(jax.grad(loss_sep)(q))
        assert gf.tobytes() == gs.tobytes()

    def test_train_step_descent_requires_pallas_fused(self):
        """The descent kwarg is the fused tier's seam: any other
        projection backend must refuse loudly, not silently diverge."""
        from d4pg_tpu.agent.d4pg import train_step

        cfg = D4PGConfig(projection_backend="xla")
        with pytest.raises(ValueError, match="pallas_fused"):
            train_step(cfg, None, None, descent=(None, None))


# --------------------------------------------------- megastep-level parity
_C, _K, _B = 64, 3, 8


def _agent_cfg(**kw) -> D4PGConfig:
    return D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(16, 16),
        dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0),
        projection_backend="pallas_fused", **kw,
    )


def _fill_buf(n=48, seed=5, cap=_C) -> ReplayBuffer:
    buf = ReplayBuffer(cap, 3, 1)
    if n == 0:
        return buf
    r = np.random.default_rng(seed)
    buf.add_batch(Transition(
        r.normal(size=(n, 3)).astype(np.float32),
        r.uniform(-1, 1, (n, 1)).astype(np.float32),
        r.uniform(-1, 0, n).astype(np.float32),
        r.normal(size=(n, 3)).astype(np.float32),
        np.full(n, 0.99, np.float32),
    ))
    return buf


def _per_setup(cfg):
    ring = device_ring_init(_C, 3, 1)
    sync = DeviceRingSync(_fill_buf(), chunk_cap=16)
    dps = dper.DevicePerSync(_C, cfg.per_alpha)
    sync.tree_hook = dps.on_chunk
    return sync.flush(ring), dps


def _run_pair(cfg, dispatches):
    """Run the separate-programs oracle and the fused tier lockstep from
    identical seeds; return their final (state, tree, key, metrics)."""
    ring_o, dps_o = _per_setup(cfg)
    ring_f, dps_f = _per_setup(cfg)
    oracle = make_megastep_device_per(cfg, _K, _B, tree_backend="pallas")
    fused = make_megastep_device_per_fused(cfg, _K, _B)
    s_o = create_train_state(cfg, jax.random.PRNGKey(1))
    s_f = create_train_state(cfg, jax.random.PRNGKey(1))
    k_o, k_f = jax.random.PRNGKey(7), jax.random.PRNGKey(7)
    t_o, t_f = dps_o.tree, dps_f.tree
    for _ in range(dispatches):
        s_o, t_o, k_o, m_o = oracle(s_o, ring_o, t_o, k_o)
        s_f, t_f, k_f, m_f = fused(s_f, ring_f, t_f, k_f)
    return (s_o, t_o, k_o, m_o), (s_f, t_f, k_f, m_f)


def _assert_pair_byte_equal(o, f):
    s_o, t_o, k_o, m_o = o
    s_f, t_f, k_f, m_f = f
    assert _leaves_equal(s_o, s_f), "TrainState diverged"
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t_o.sums)),
        np.asarray(jax.device_get(t_f.sums)),
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t_o.max_priority)),
        np.asarray(jax.device_get(t_f.max_priority)),
    )
    np.testing.assert_array_equal(np.asarray(k_o), np.asarray(k_f))
    for k in m_o:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(m_o[k])),
            np.asarray(jax.device_get(m_f[k])), err_msg=k,
        )


class TestFusedMegastepParity:
    def test_byte_identical_vs_separate_programs(self):
        """Whole-TrainState + tree + key + metrics byte parity over 3
        donated dispatches: the fused tier IS the oracle, relocated."""
        o, f = _run_pair(_agent_cfg(), dispatches=3)
        _assert_pair_byte_equal(o, f)

    def test_byte_identical_bf16_ensemble(self):
        """The flagship recipe's compute path — bf16 trunks + stacked
        REDQ ensemble — stays byte-identical fused-vs-oracle too (the
        descent pipelining is orthogonal to what the loss computes)."""
        cfg = _agent_cfg(
            compute_dtype="bfloat16", critic_ensemble=2,
            ensemble_min_targets=2,
        )
        o, f = _run_pair(cfg, dispatches=2)
        _assert_pair_byte_equal(o, f)

    def test_bf16_recipe_within_pinned_tolerance_of_f32(self):
        """The recipe's end-to-end bf16 claim vs the f32 reference at
        pinned tolerances: one dispatch (same PRNG → same draws, the tree
        only updates post-scan), losses within 5% + 0.02, f32-master
        params within 1e-3 after K grad steps."""
        ring_a, dps_a = _per_setup(_agent_cfg())
        ring_b, dps_b = _per_setup(_agent_cfg())
        f32 = make_megastep_device_per_fused(_agent_cfg(), _K, _B)
        bf16 = make_megastep_device_per_fused(
            _agent_cfg(compute_dtype="bfloat16"), _K, _B
        )
        s_a = create_train_state(_agent_cfg(), jax.random.PRNGKey(1))
        s_b = create_train_state(
            _agent_cfg(compute_dtype="bfloat16"), jax.random.PRNGKey(1)
        )
        s_a, _, _, m_a = f32(s_a, ring_a, dps_a.tree, jax.random.PRNGKey(7))
        s_b, _, _, m_b = bf16(s_b, ring_b, dps_b.tree, jax.random.PRNGKey(7))
        np.testing.assert_allclose(
            np.asarray(jax.device_get(m_a["critic_loss"])),
            np.asarray(jax.device_get(m_b["critic_loss"])),
            rtol=0.05, atol=0.02,
        )
        for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(s_a.critic_params)),
            jax.tree_util.tree_leaves(jax.device_get(s_b.critic_params)),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), atol=1e-3
            )


# ------------------------------------------------ double-buffered ingest
class TestIngestStaging:
    def _pair(self, cap=_C, chunk_cap=16):
        """Two identical (buffer, ring, sync) triples with slot-recording
        tree hooks."""
        out = []
        for _ in range(2):
            buf = _fill_buf(0, cap=cap)
            sync = DeviceRingSync(buf, chunk_cap=chunk_cap)
            seen = []
            sync.tree_hook = lambda s, seen=seen: seen.append(
                np.asarray(jax.device_get(s)).copy()
            )
            out.append((buf, device_ring_init(cap, 3, 1), sync, seen))
        return out

    def _add(self, buf, n, seed):
        r = np.random.default_rng(seed)
        buf.add_batch(Transition(
            r.normal(size=(n, 3)).astype(np.float32),
            r.uniform(-1, 1, (n, 1)).astype(np.float32),
            r.uniform(-1, 0, n).astype(np.float32),
            r.normal(size=(n, 3)).astype(np.float32),
            np.full(n, 0.99, np.float32),
        ))

    def test_stage_then_flush_byte_equal_plain_flush(self):
        """stage()+flush() is invisible: same ring bytes, same tree-hook
        slot sequence, same byte/chunk counters as a plain flush."""
        (buf_a, ring_a, sync_a, seen_a), (buf_b, ring_b, sync_b, seen_b) = (
            self._pair()
        )
        self._add(buf_a, 48, seed=5)
        self._add(buf_b, 48, seed=5)
        ring_a = sync_a.flush(ring_a)
        assert sync_b.stage()
        ring_b = sync_b.flush(ring_b)
        assert _leaves_equal(ring_a, ring_b)
        assert sync_a._synced == sync_b._synced == 48
        assert sync_a.bytes_ingested == sync_b.bytes_ingested
        assert sync_a.chunks_ingested == sync_b.chunks_ingested == 3
        assert len(seen_a) == len(seen_b)
        for x, y in zip(seen_a, seen_b):
            np.testing.assert_array_equal(x, y)

    def test_stage_survives_ring_wrap_overwrites(self):
        """Rows overwritten between stage() and flush() are re-shipped by
        the remainder loop AFTER the staged scatter — the mirrored ring
        equals a from-scratch full mirror (last-write-wins)."""
        (buf_a, ring_a, sync_a, _), (buf_b, ring_b, sync_b, _) = self._pair()
        for buf in (buf_a, buf_b):
            self._add(buf, 48, seed=5)
        ring_a = sync_a.flush(ring_a)
        # 10 fresh rows get staged; then 70 more writes wrap the 64-row
        # ring and overwrite every staged slot before the flush.
        for buf, seed in ((buf_a, 11), (buf_b, 11)):
            self._add(buf, 10, seed=seed)
        assert sync_b.stage()
        for buf, seed in ((buf_a, 12), (buf_b, 12)):
            self._add(buf, 70, seed=seed)
        ring_a = sync_a.flush(ring_a)
        ring_b = sync_b.flush(ring_b)
        assert _leaves_equal(ring_a, ring_b)
        # And both equal a from-scratch mirror of the final buffer state.
        buf_c = _fill_buf(0)
        self._add(buf_c, 48, seed=5)
        self._add(buf_c, 10, seed=11)
        self._add(buf_c, 70, seed=12)
        sync_c = DeviceRingSync(buf_c, chunk_cap=16)
        ring_c = sync_c.flush(device_ring_init(_C, 3, 1))
        assert _leaves_equal(ring_a, ring_c)

    def test_stage_noop_and_single_consume(self):
        (buf, ring, sync, _), _ = self._pair()
        assert not sync.stage()          # nothing pending
        self._add(buf, 10, seed=3)
        assert sync.stage()
        assert sync.stage()              # idempotent while staged
        ring = sync.flush(ring)
        assert sync.chunks_ingested == 1  # staged chunk covered it all
        assert sync._staged is None
        assert int(np.asarray(jax.device_get(ring.size))) == 10
        assert sync.flush(ring) is ring  # nothing left pending


# ------------------------------------------------------ recipe + gating
class TestBatchScaleRecipe:
    def test_scaling_rules_pinned(self):
        cfg = apply_env_preset(TrainConfig(env="pendulum", batch_scale=8))
        s = apply_batch_scale(cfg)
        assert s.batch_size == 2048
        assert s.agent.lr_actor == pytest.approx(8e-4)
        assert s.agent.lr_critic == pytest.approx(8e-4)
        assert s.agent.per_beta_steps == 100_000 // 8
        assert s.warmup_steps == 8_000
        assert s.steps_per_dispatch == 1
        # K floors at 1 but divides when it can
        s2 = apply_batch_scale(dataclasses.replace(
            cfg, batch_scale=4, steps_per_dispatch=8
        ))
        assert s2.steps_per_dispatch == 2

    def test_scale_one_is_identity(self):
        cfg = apply_env_preset(TrainConfig(env="pendulum"))
        assert apply_batch_scale(cfg) == cfg

    def test_cli_wires_the_recipe(self):
        from train import build_parser, config_from_args

        args = build_parser().parse_args([
            "--env", "pendulum", "--batch-scale", "8",
            "--replay-placement", "device", "--projection", "pallas_fused",
            "--fused-descent", "--ingest-prefetch",
        ])
        cfg = config_from_args(args)
        assert cfg.batch_size == 2048 and cfg.batch_scale == 8
        assert cfg.agent.lr_actor == pytest.approx(8e-4)
        assert cfg.fused_descent and cfg.ingest_prefetch


class TestFusedNegotiation:
    def test_fused_descent_verdicts(self):
        ok = RequestedCaps(placement="device", fused_descent=True,
                           projection="pallas_fused")
        assert negotiate(ok).verdict == "pass"
        codes = {
            g.code for g in negotiate(RequestedCaps(
                placement="host", fused_descent=True
            )).gaps
        }
        assert {"fused_descent_device_only",
                "fused_descent_requires_pallas_fused"} <= codes
        assert "fused_descent_single_device" in {
            g.code for g in negotiate(dataclasses.replace(ok, dp=2)).gaps
        }
        assert "fused_descent_requires_per" in {
            g.code for g in negotiate(
                dataclasses.replace(ok, prioritized=False)
            ).gaps
        }
        assert "fused_descent_categorical_only" in {
            g.code for g in negotiate(
                dataclasses.replace(ok, dist_kind="quantile")
            ).gaps
        }

    def test_ingest_prefetch_declared(self):
        n = negotiate(RequestedCaps(placement="host", ingest_prefetch=True))
        assert n.verdict == "negotiated"
        assert "ingest_prefetch_ignored" in n.actions
        assert negotiate(
            RequestedCaps(placement="device", ingest_prefetch=True)
        ).verdict == "pass"

    def test_matrix_declares_large_batch_scenario(self):
        cells = {
            (c["scenario"], c["placement"]): c for c in composition_matrix()
        }
        assert cells[("large_batch_fused", "device")]["verdict"] == "pass"
        assert cells[("large_batch_fused", "host")]["verdict"] == "gap"


# ------------------------------------------------------- trainer-level
def _recipe_trainer_cfg(log_dir: str, **kw) -> TrainConfig:
    agent = D4PGConfig(
        hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11),
        projection_backend="pallas_fused",
    )
    base = dict(
        env="pendulum", num_envs=2, total_steps=8, warmup_steps=48,
        batch_size=8, steps_per_dispatch=2, eval_interval=1000,
        eval_episodes=1, checkpoint_interval=100_000, replay_capacity=512,
        prioritized=True, tree_backend="numpy", agent=agent,
        log_dir=log_dir, concurrent_eval=False, seed=3,
        replay_placement="device", device_tree_backend="pallas",
        fused_descent=True, ingest_prefetch=True, debug_guards=True,
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


def _run_trainer(cfg):
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(cfg)
    try:
        t.train()
        return t, jax.device_get(t.state)
    finally:
        t.close()


class TestFusedTrainerGuards:
    def test_fused_recipe_guards_clean(self, tmp_path):
        """THE fast end-to-end smoke of the whole ISSUE-16 stack: fused
        descent + double-buffered ingest under --debug-guards. Sentinel
        budgets hold (megastep/ring_ingest/tree_ingest all compile ONCE),
        the zero-transfer steady state is clean, no ledger hold leaks,
        and the ingest_stage timer actually ran."""
        t, _ = _run_trainer(_recipe_trainer_cfg(str(tmp_path / "fused")))
        assert t._megastep_warm
        counts = t.sentinel.counts()
        assert counts["megastep"] == 1
        assert counts["ring_ingest"] == 1
        assert counts["tree_ingest"] == 1
        assert t._ledger.stats()["active_holds"] == 0
        assert t._ledger.stats()["trips"] == 0
        row = t._timers.scalars()
        assert row["stage_ingest_stage_calls"] > 0

    def test_fused_trainer_byte_equal_oracle_trainer(self, tmp_path):
        """Flipping --fused-descent (and --ingest-prefetch with it) moves
        NOTHING in a seeded device-PER run: byte-identical params and
        optimizer moments after a full train() leg."""
        _, s_fused = _run_trainer(
            _recipe_trainer_cfg(str(tmp_path / "fused"))
        )
        _, s_oracle = _run_trainer(_recipe_trainer_cfg(
            str(tmp_path / "oracle"), fused_descent=False,
            ingest_prefetch=False,
        ))
        assert _leaves_equal(s_fused.actor_params, s_oracle.actor_params)
        assert _leaves_equal(s_fused.critic_params, s_oracle.critic_params)
        assert _leaves_equal(
            s_fused.critic_opt_state, s_oracle.critic_opt_state
        )

    @pytest.mark.slow
    def test_large_batch_400_step_guards_clean(self, tmp_path):
        """The ISSUE-16 acceptance run: 400 grad steps at the large-batch
        shape (B=2048, bf16, ensemble off to bound wall time) under
        --debug-guards — zero guard trips, zero leaked holds, budgets
        megastep=1 / ring_ingest=1 / tree_ingest=1."""
        agent = D4PGConfig(
            hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11),
            projection_backend="pallas_fused", compute_dtype="bfloat16",
        )
        t, _ = _run_trainer(_recipe_trainer_cfg(
            str(tmp_path / "big"), agent=agent, num_envs=4,
            total_steps=400, warmup_steps=2500, batch_size=2048,
            steps_per_dispatch=4, replay_capacity=4096,
        ))
        assert t._megastep_warm
        counts = t.sentinel.counts()
        assert counts["megastep"] == 1
        assert counts["ring_ingest"] == 1
        assert counts["tree_ingest"] == 1
        assert t._ledger.stats()["active_holds"] == 0
        assert t._ledger.stats()["trips"] == 0

    @pytest.mark.slow
    def test_scaled_recipe_solve_quality_parity(self, tmp_path):
        """Solve-quality parity on pendulum: the --batch-scale 4 recipe
        (B=512, lr x4, beta-anneal /4, warmup x4) at the SAME data budget
        as the integration baseline must clear the same learning bar
        (trained beats random init by > 250 return)."""
        from train import build_parser, config_from_args
        from d4pg_tpu.envs import Pendulum
        from d4pg_tpu.runtime import evaluate

        args = build_parser().parse_args([
            "--env", "pendulum",
            "--total-steps", "1500",      # 6000 baseline steps / S=4
            "--warmup", "2000",           # recipe scales this x4
            "--eval-interval", "100000",
            "--checkpoint-interval", "1000000",
            "--num-envs", "8",
            "--bsize", "128",             # recipe scales this to 512
            "--batch-scale", "4",
            "--n-step", "3",
            "--tau", "0.005",
            "--lr-actor", "5e-4",         # recipe scales to 2e-3
            "--lr-critic", "5e-4",
            "--seed", "0",
            "--replay-placement", "device",
            "--device-tree-backend", "pallas",
            "--projection", "pallas_fused",
            "--fused-descent",
            "--ingest-prefetch",
            "--rmsize", "16384",
            "--log-dir", str(tmp_path / "recipe"),
        ])
        cfg = config_from_args(args)
        cfg = dataclasses.replace(
            cfg,
            agent=dataclasses.replace(cfg.agent, hidden_sizes=(64, 64)),
            # same env-interaction budget as the baseline: 2.0 x S
            env_steps_per_train_step=8.0,
        )
        base_state = create_train_state(cfg.agent, jax.random.PRNGKey(123))
        base = evaluate(
            cfg.agent, Pendulum(), base_state.actor_params,
            jax.random.PRNGKey(7), 10,
        )
        trainer, state = _run_trainer(cfg)
        trained = evaluate(
            cfg.agent, Pendulum(), state.actor_params,
            jax.random.PRNGKey(7), 10,
        )
        improvement = trained["eval_return_mean"] - base["eval_return_mean"]
        assert improvement > 250.0, (
            f"scaled recipe lost solve quality: random "
            f"{base['eval_return_mean']:.0f} -> trained "
            f"{trained['eval_return_mean']:.0f}"
        )


# --------------------------------------------- committed artifact + schema
class TestMfuSweepArtifact:
    """The committed large-batch recipe row (benchmarks/
    mfu_sweep_results.json) and the lint gate that refuses to lose it."""

    ARTIFACT = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "mfu_sweep_results.json",
    )

    def _rows(self):
        with open(self.ARTIFACT) as f:
            return json.load(f)

    def test_committed_large_batch_row(self):
        rows = self._rows()
        lb = [
            r for r in rows
            if str(r.get("config", "")).startswith("large_batch")
        ]
        assert lb, "mfu_sweep_results.json lost its large-batch recipe row"
        for r in lb:
            assert r["bench"] == "mfu_sweep"
            assert "backend" in r  # CPU placeholders must be distinguishable
            assert r["batch"] >= 2048  # the MXU-filling shape, not a toy
            assert r["compute_dtype"] == "bfloat16"
            assert r["transfer_bytes_per_grad_step"] == 0.0
            assert r["steps_per_sec"] > 0
            # the >=2x-flagship-MFU claim, anchored to on-chip rows
            assert r["mfu_onchip_proxy"]["ratio_vs_flagship"] >= 2.0
            # the ready-to-run on-chip recipe is the row's other half
            assert "--fused-descent" in r["recipe"]
            assert "--batch-scale" in r["recipe"]
        # every other family survived the --large-batch-only regen
        for family in ("mlp256", "megastep_mlp256", "device_per_megastep",
                       "sharded_megastep"):
            assert any(
                str(r.get("config", "")).startswith(family) for r in rows
            ), f"--large-batch-only regen clobbered the {family} family"

    def test_schema_check_accepts_committed_and_refuses_mutants(self, tmp_path):
        from tools.d4pglint.schema_check import check_mfu_sweep

        assert check_mfu_sweep(self.ARTIFACT) == []
        rows = self._rows()

        def _write(mutant_rows):
            p = tmp_path / "mfu_sweep_results.json"
            p.write_text(json.dumps(mutant_rows))
            return str(p)

        # dropping the row (a regen without --large-batch) must fail lint
        errs = check_mfu_sweep(_write([
            r for r in rows
            if not str(r.get("config", "")).startswith("large_batch")
        ]))
        assert errs and "large-batch" in errs[0]
        # nonzero transfer bytes on the fused tier must fail lint
        bad = json.loads(json.dumps(rows))
        for r in bad:
            if str(r.get("config", "")).startswith("large_batch"):
                r["transfer_bytes_per_grad_step"] = 12.0
        assert any(
            "zero-transfer" in e for e in check_mfu_sweep(_write(bad))
        )
        # a sub-MXU batch or a sub-2x proxy is not the committed claim
        bad = json.loads(json.dumps(rows))
        for r in bad:
            if str(r.get("config", "")).startswith("large_batch"):
                r["batch"] = 256
                r["mfu_onchip_proxy"]["ratio_vs_flagship"] = 1.3
        errs = check_mfu_sweep(_write(bad))
        assert any("B >= 2048" in e for e in errs)
        assert any("2x the flagship" in e for e in errs)
