"""Device-resident replay + fused megastep (ROADMAP item 1).

The contracts under test, in dependency order:

1. the HBM ring is a byte-exact mirror of the host buffer's slots,
   through chunked ingest, ring wrap, and snapshot restore, with exactly
   ONE ingest compile;
2. seeded small-scale f32 parity: the device ring's uniform path (in-
   kernel ``jax.random`` draw) produces a BYTE-IDENTICAL TrainState vs
   the host oracle (host-gathered batches through the same fused scan)
   given the same key — the acceptance contract of the megastep;
3. frozen-literal hybrid determinism: ``sample_block_indices`` draws the
   exact pinned index stream, equal to ``sample_block``'s on every tree
   backend — so flipping ``replay_placement`` host↔hybrid moves no
   seeded run (and a full two-Trainer run proves it end to end,
   byte-identical params included);
4. the trainer's device placement runs clean under ``--debug-guards``
   with the TIGHTENED zero-transfer budget (no H2D — explicit or
   implicit — and no D2H at the steady-state dispatch site), zero
   recompiles after warmup, zero leaked ledger holds;
5. placement validation: the flag surface fails loudly on unsupported
   combinations instead of silently ignoring them.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from d4pg_tpu.agent import D4PGConfig, create_train_state  # noqa: E402
from d4pg_tpu.agent.d4pg import fused_train_scan  # noqa: E402
from d4pg_tpu.config import TrainConfig, apply_env_preset  # noqa: E402
from d4pg_tpu.models.critic import DistConfig  # noqa: E402
from d4pg_tpu.replay.device_ring import (  # noqa: E402
    DeviceRingSync,
    device_ring_init,
)
from d4pg_tpu.replay.per import PrioritizedReplayBuffer  # noqa: E402
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition  # noqa: E402
from d4pg_tpu.runtime.megastep import (  # noqa: E402
    draw_uniform_indices,
    make_megastep_uniform,
)


def _small_cfg() -> D4PGConfig:
    return D4PGConfig(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(16, 16),
        dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0),
    )


def _fill(buf, n, seed=0):
    r = np.random.default_rng(seed)
    obs_dim = buf.obs.shape[1]
    act_dim = buf.action.shape[1]
    buf.add_batch(
        Transition(
            r.normal(size=(n, obs_dim)).astype(np.float32),
            r.uniform(-1, 1, (n, act_dim)).astype(np.float32),
            r.uniform(-1, 0, n).astype(np.float32),
            r.normal(size=(n, obs_dim)).astype(np.float32),
            np.full(n, 0.99, np.float32),
        )
    )


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb)
    )


# ------------------------------------------------------------- ring mirror
class TestDeviceRingMirror:
    def test_mirror_matches_host_slots(self):
        buf = ReplayBuffer(32, 3, 1)
        _fill(buf, 20)
        ring = device_ring_init(32, 3, 1)
        sync = DeviceRingSync(buf, chunk_cap=8)  # forces multi-chunk flush
        ring = sync.flush(ring)
        assert int(ring.size) == 20
        for field in ("obs", "action", "reward", "next_obs", "discount"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ring, field))[:20], getattr(buf, field)[:20]
            )

    def test_mirror_through_ring_wrap(self):
        buf = ReplayBuffer(16, 3, 1)
        ring = device_ring_init(16, 3, 1)
        sync = DeviceRingSync(buf, chunk_cap=8)
        _fill(buf, 10, seed=1)
        ring = sync.flush(ring)
        _fill(buf, 10, seed=2)  # wraps: slots 10..15, then 0..3
        ring = sync.flush(ring)
        assert int(ring.size) == 16
        for field in ("obs", "reward"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ring, field)), getattr(buf, field)
            )

    def test_pending_beyond_capacity_collapses_to_full_resync(self):
        buf = ReplayBuffer(8, 3, 1)
        ring = device_ring_init(8, 3, 1)
        sync = DeviceRingSync(buf, chunk_cap=8)
        _fill(buf, 30, seed=3)  # 30 writes into an 8-slot ring
        assert sync.pending() == 8  # only the surviving slots ship
        ring = sync.flush(ring)
        np.testing.assert_array_equal(np.asarray(ring.obs), buf.obs)
        assert sync.pending() == 0

    def test_flush_noop_when_nothing_pending(self):
        buf = ReplayBuffer(16, 3, 1)
        _fill(buf, 4)
        ring = device_ring_init(16, 3, 1)
        sync = DeviceRingSync(buf)
        ring = sync.flush(ring)
        chunks = sync.chunks_ingested
        ring = sync.flush(ring)  # nothing new
        assert sync.chunks_ingested == chunks

    def test_single_ingest_compile_across_flushes(self):
        buf = ReplayBuffer(64, 3, 1)
        ring = device_ring_init(64, 3, 1)
        sync = DeviceRingSync(buf, chunk_cap=16)
        for seed in range(4):
            _fill(buf, 10, seed=seed)
            ring = sync.flush(ring)
        # one fixed chunk shape -> exactly one compiled specialization
        # (the recompile sentinel budgets this at 1 in --debug-guards runs)
        assert sync.ingest_fn._cache_size() == 1

    def test_restore_resyncs_whole_buffer(self, tmp_path):
        src = ReplayBuffer(16, 3, 1)
        _fill(src, 12, seed=9)
        snap = str(tmp_path / "replay.npz")
        src.snapshot(snap)
        dst = ReplayBuffer(16, 3, 1)
        dst.restore(snap)
        ring = device_ring_init(16, 3, 1)
        sync = DeviceRingSync(dst, chunk_cap=8)
        ring = sync.flush(ring)
        assert int(ring.size) == 12
        np.testing.assert_array_equal(np.asarray(ring.obs)[:12], dst.obs[:12])


# ------------------------------------------------- uniform megastep parity
class TestUniformMegastepParity:
    def test_byte_identical_vs_host_oracle(self):
        """The acceptance contract: same transitions + same seeded key ⇒
        the uniform megastep (in-kernel draw + in-jit ring gather) and the
        host oracle (host-gathered staged batches through the same fused
        scan) produce byte-identical TrainStates after N dispatches, f32,
        small scale. Depends on the uniform path carrying NO weights key
        on either side (see megastep_uniform_body's determinism note)."""
        from functools import partial

        cfg = _small_cfg()
        K, B, rows = 3, 8, 64
        buf = ReplayBuffer(128, 3, 1)
        _fill(buf, rows)
        ring = DeviceRingSync(buf, chunk_cap=32).flush(
            device_ring_init(128, 3, 1)
        )
        mega = make_megastep_uniform(cfg, K, B)
        fused = jax.jit(partial(fused_train_scan, cfg), donate_argnums=(0,))
        state_dev = create_train_state(cfg, jax.random.PRNGKey(1))
        state_host = create_train_state(cfg, jax.random.PRNGKey(1))
        key = jax.random.PRNGKey(7)
        k = key
        for _ in range(3):
            # oracle: replicate the in-kernel draw on host (threefry is
            # backend-deterministic), gather host-side, stage, scan
            _, k_idx = jax.random.split(k)
            idx = np.asarray(draw_uniform_indices(k_idx, K, B, jnp.int32(rows)))
            batches = {
                name: jnp.asarray(
                    np.stack([getattr(buf, name)[idx[i]] for i in range(K)])
                )
                for name in ("obs", "action", "reward", "next_obs", "discount")
            }
            state_host, _, _ = fused(state_host, batches)
            state_dev, k, _metrics = mega(state_dev, ring, k)
        # the WHOLE TrainState: params, targets, both Adam moment sets
        assert _leaves_equal(state_dev, state_host)

    def test_different_keys_diverge(self):
        """Sanity for the parity test: the comparison is not vacuous."""
        cfg = _small_cfg()
        buf = ReplayBuffer(128, 3, 1)
        _fill(buf, 64)
        ring = DeviceRingSync(buf).flush(device_ring_init(128, 3, 1))
        mega = make_megastep_uniform(cfg, 2, 8)
        s1, _, _ = mega(
            create_train_state(cfg, jax.random.PRNGKey(1)), ring,
            jax.random.PRNGKey(7),
        )
        s2, _, _ = mega(
            create_train_state(cfg, jax.random.PRNGKey(1)), ring,
            jax.random.PRNGKey(8),
        )
        assert not _leaves_equal(s1.actor_params, s2.actor_params)


# ------------------------------------------------ hybrid index determinism
def _per_buf(backend: str) -> PrioritizedReplayBuffer:
    buf = PrioritizedReplayBuffer(64, 3, 2, tree_backend=backend)
    r = np.random.default_rng(5)
    buf.add_batch(
        Transition(
            r.normal(size=(48, 3)).astype(np.float32),
            r.uniform(-1, 1, (48, 2)).astype(np.float32),
            r.uniform(-1, 0, 48).astype(np.float32),
            r.normal(size=(48, 3)).astype(np.float32),
            np.full(48, 0.99, np.float32),
        )
    )
    buf.update_priorities(
        np.arange(48), r.uniform(0.1, 3.0, 48).astype(np.float64)
    )
    return buf


# The determinism contract, frozen: this exact seeded buffer + rng(123) +
# B=4, K=3, step=7 must draw THESE indices forever — the stream
# sample_block consumes (one uniform of size K·B over stratified bounds,
# round-robin dealt). If this literal moves, seeded PER runs diverge when
# flipping replay_placement between host and hybrid.
FROZEN_HYBRID_IDX = [[3, 12, 26, 39], [4, 16, 27, 42], [9, 21, 34, 45]]


class TestHybridIndexDeterminism:
    @pytest.mark.parametrize("backend", ["numpy", "auto"])
    def test_frozen_literal_and_sample_block_equality(self, backend):
        buf = _per_buf(backend)
        idx, w, gen = buf.sample_block_indices(
            4, 3, np.random.default_rng(123), step=7
        )
        assert idx.tolist() == FROZEN_HYBRID_IDX
        blk = _per_buf(backend).sample_block(
            4, 3, np.random.default_rng(123), step=7
        )
        np.testing.assert_array_equal(blk["indices"].idx, idx)
        np.testing.assert_array_equal(blk["indices"].gen, gen)
        np.testing.assert_array_equal(blk["weights"], w)


# ------------------------------------------------- trainer-level contracts
def _trainer_cfg(placement: str, log_dir: str, **kw) -> TrainConfig:
    agent = D4PGConfig(hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11))
    base = dict(
        env="pendulum",
        num_envs=2,
        total_steps=8,
        warmup_steps=48,
        batch_size=8,
        steps_per_dispatch=2,
        eval_interval=1000,
        eval_episodes=1,
        checkpoint_interval=100_000,
        replay_capacity=512,
        prioritized=True,
        tree_backend="numpy",
        agent=agent,
        log_dir=log_dir,
        concurrent_eval=False,
        seed=3,
        replay_placement=placement,
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


def _run_trainer(cfg):
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(cfg)
    try:
        t.train()
        return t, jax.device_get(t.state)
    finally:
        t.close()


class TestTrainerPlacement:
    @pytest.mark.slow
    def test_hybrid_byte_identical_to_host(self, tmp_path):
        """Flipping replay_placement host↔hybrid moves NOTHING in a seeded
        run: same PER index stream (sample_block_indices == sample_block),
        same rows (ring mirrors the host buffer byte-exactly), same IS
        weights ⇒ byte-identical params, targets, and optimizer moments
        after a full train() leg on a real env."""
        _, s_host = _run_trainer(
            _trainer_cfg("host", str(tmp_path / "host"))
        )
        _, s_hyb = _run_trainer(
            _trainer_cfg("hybrid", str(tmp_path / "hyb"))
        )
        assert _leaves_equal(s_host.actor_params, s_hyb.actor_params)
        assert _leaves_equal(s_host.critic_params, s_hyb.critic_params)
        assert _leaves_equal(s_host.actor_opt_state, s_hyb.actor_opt_state)

    def test_device_placement_guards_clean(self, tmp_path):
        """Device placement under --debug-guards: the steady-state
        dispatch runs under the TIGHTENED zero-transfer budget
        (no_transfers: explicit H2D and any D2H raise), the recompile
        budget holds after warmup, and no ledger hold leaks."""
        t, _ = _run_trainer(
            _trainer_cfg(
                "device", str(tmp_path / "dev"), prioritized=False,
                debug_guards=True,
            )
        )
        assert t._megastep_warm  # steady-state dispatches ran tight-guarded
        counts = t.sentinel.counts()
        assert counts["megastep"] == 1
        assert counts["ring_ingest"] == 1
        assert t._ledger.stats()["active_holds"] == 0
        assert t._ledger.stats()["trips"] == 0

    def test_device_metrics_row_has_zero_count_h2d(self, tmp_path):
        """The ride-along bugfix: device-placement metrics rows carry the
        per-dispatch host stages as EXPLICIT zeros (0 s / 0 calls), and
        the megastep stages as live counters."""
        t, _ = _run_trainer(
            _trainer_cfg("device", str(tmp_path / "dev"), prioritized=False)
        )
        row = t._timers.scalars()
        assert row["stage_h2d_stage_calls"] == 0.0
        assert row["stage_h2d_stage_s"] == 0.0
        assert row["stage_sample_calls"] == 0.0
        assert row["stage_megastep_dispatch_calls"] > 0
        assert row["stage_ingest_chunk_calls"] > 0

    def test_placement_validation(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        with pytest.raises(ValueError, match="hybrid is the PER mode"):
            Trainer(
                _trainer_cfg(
                    "hybrid", str(tmp_path / "a"), prioritized=False
                )
            )
        with pytest.raises(ValueError, match="transfer-dtype|transfer_dtype"):
            Trainer(
                _trainer_cfg(
                    "device", str(tmp_path / "b"), prioritized=False,
                    transfer_dtype="bfloat16",
                )
            )
        with pytest.raises(ValueError, match="host|device|hybrid"):
            Trainer(_trainer_cfg("gpu", str(tmp_path / "c")))

    def test_no_transfers_guard_catches_injected_violations(self):
        """The tightened budget is a real guard, not a comment: an
        explicit device_put (sanctioned under the old budget) and a D2H
        fetch both raise inside no_transfers; the megastep dispatch
        itself passes (its operands are device-resident)."""
        from d4pg_tpu.analysis import no_transfers

        cfg = _small_cfg()
        buf = ReplayBuffer(64, 3, 1)
        _fill(buf, 32)
        ring = DeviceRingSync(buf).flush(device_ring_init(64, 3, 1))
        mega = make_megastep_uniform(cfg, 2, 4)
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        key = jax.device_put(jax.random.PRNGKey(1))
        state, key, _ = mega(state, ring, key)  # warmup compile (exempt)
        with no_transfers():
            state, key, metrics = mega(state, ring, key)  # clean
        with pytest.raises(Exception, match="[Dd]isallowed.*transfer"):
            with no_transfers():
                jax.device_put(np.zeros(4, np.float32))  # explicit H2D
        if jax.default_backend() != "cpu":
            # On the CPU backend a fetch is zero-copy (no transfer event
            # fires), so the D2H half is only assertable on a real device.
            with pytest.raises(Exception, match="[Dd]isallowed.*transfer"):
                with no_transfers():
                    np.asarray(metrics["critic_loss"])

    def test_device_keeps_prioritized_on_device(self, tmp_path, capsys):
        """ISSUE 14: `--replay-placement device` with the default PER
        flag KEEPS prioritized replay — the priority structure is the
        device-resident segment tree (tests/test_device_per.py has the
        full contract), the host buffer a plain ring, no downgrade."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_trainer_cfg("device", str(tmp_path / "d")))
        try:
            assert t.config.prioritized is True
            assert isinstance(t.buffer, ReplayBuffer)
            assert not isinstance(t.buffer, PrioritizedReplayBuffer)
            assert t._dev_per is not None
        finally:
            t.close()
        assert "disabling PER" not in capsys.readouterr().out
