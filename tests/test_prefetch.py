"""Double-buffered replay→device prefetch pipeline (round 6).

Covers the two halves of the tentpole's input pipeline:

- ``PrioritizedReplayBuffer.sample_many`` — one locked K·B-wide stratified
  descent dealt round-robin into K batches (per-batch full-mass coverage,
  shared generation capture, write-back semantics);
- the trainer's ``config.prefetch`` double buffer — batch ordering, PER
  generation stamps, and end-to-end training with the buffer on, for both
  K=1 and fused K>1 dispatches.
"""

import dataclasses

import numpy as np
import pytest

from d4pg_tpu.replay import PrioritizedReplayBuffer


def _fill(buf, n, obs_dim=1, act_dim=1, rng=None):
    rng = rng or np.random.default_rng(0)
    for i in range(n):
        buf.add(
            np.full(obs_dim, float(i)),
            rng.normal(size=act_dim),
            float(i),
            rng.normal(size=obs_dim),
            0.99,
        )


def test_sample_many_shapes_and_batch_independence():
    buf = PrioritizedReplayBuffer(128, 3, 2, tree_backend="numpy")
    rng_fill = np.random.default_rng(0)
    for i in range(100):
        buf.add(
            rng_fill.normal(size=3), rng_fill.normal(size=2), float(i),
            rng_fill.normal(size=3), 0.99,
        )
    K, B = 4, 16
    batches = buf.sample_many(B, K, np.random.default_rng(1), step=0)
    assert len(batches) == K
    for b in batches:
        assert b["obs"].shape == (B, 3)
        assert b["action"].shape == (B, 2)
        assert b["weights"].shape == (B,)
        assert b["indices"].idx.shape == (B,)
        assert b["indices"].gen.shape == (B,)
        # uniform priorities → all IS weights 1 (same as sample())
        np.testing.assert_allclose(b["weights"], 1.0, atol=1e-6)


def test_sample_many_each_batch_covers_full_priority_mass():
    """Round-robin dealing: EVERY batch must span the whole mass, not a
    contiguous 1/K slice (a contiguous split would give batch 0 only the
    low-index region of the ring)."""
    buf = PrioritizedReplayBuffer(64, 1, 1, alpha=1.0, tree_backend="numpy")
    _fill(buf, 64)
    # uniform mass: a full-range stratified batch of B=16 over 64 equal
    # leaves must touch all four quarters of the ring in every batch
    batches = buf.sample_many(16, 4, np.random.default_rng(2), step=0)
    for b in batches:
        quarters = set(np.asarray(b["indices"].idx) // 16)
        assert quarters == {0, 1, 2, 3}


def test_sample_many_proportionality_matches_sample():
    buf = PrioritizedReplayBuffer(64, 1, 1, alpha=1.0, tree_backend="numpy")
    _fill(buf, 10)
    pri = np.full(10, 1e-3)
    pri[3] = 1e3
    buf.update_priorities(np.arange(10), pri)
    batches = buf.sample_many(64, 4, np.random.default_rng(3), step=0)
    for b in batches:
        frac3 = np.mean(b["obs"][:, 0] == 3.0)
        assert frac3 > 0.9


def test_sample_many_generation_stamps_drop_recycled_slots():
    """The prefetch hazard in miniature: a staged (prefetched) multi-batch
    sample is written back AFTER the collector recycled the ring — every
    stale entry must be dropped, exactly as single-batch sampling does."""
    buf = PrioritizedReplayBuffer(8, 1, 1, alpha=1.0, eps=0.0, tree_backend="numpy")
    _fill(buf, 8)
    batches = buf.sample_many(4, 2, np.random.default_rng(0), step=0)
    _fill(buf, 8)  # recycle the whole ring while the "dispatch" runs
    seed = buf._max_priority
    for b in batches:
        buf.update_priorities(b["indices"], np.full(4, 1e-6))
    np.testing.assert_allclose(buf._sum.get(np.arange(8)), seed, atol=1e-9)
    # live slots (no recycle) still apply
    batches = buf.sample_many(4, 2, np.random.default_rng(1), step=0)
    buf.update_priorities(batches[0]["indices"], np.full(4, 0.5))
    assert buf._min.min() == pytest.approx(0.5)


@pytest.mark.parametrize("steps_per_dispatch", [1, 4])
def test_trainer_prefetch_end_to_end(tmp_path, steps_per_dispatch):
    """Trainer with the double buffer on: exact grad-step accounting,
    finite metrics, and PER priorities actually written back (the tree
    must leave its fresh-insert seed state despite the one-dispatch lag)."""
    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = TrainConfig(
        env="pendulum",
        total_steps=8,
        warmup_steps=32,
        batch_size=16,
        num_envs=2,
        eval_interval=1000,
        checkpoint_interval=1000,
        steps_per_dispatch=steps_per_dispatch,
        prefetch=True,
        log_dir=str(tmp_path / f"pf{steps_per_dispatch}"),
        agent=D4PGConfig(hidden_sizes=(16, 16)),
    )
    cfg = apply_env_preset(cfg)
    t = Trainer(cfg)
    try:
        out = t.train()
        assert t.grad_steps == 8
        assert np.isfinite(out["critic_loss"])
        # priorities were written back: with per_eps>0 the CE-based leaves
        # cannot all still equal the max-priority insert seed
        n = len(t.buffer)
        leaves = t.buffer._sum.get(np.arange(n))
        seed = t.buffer._max_priority ** t.buffer.alpha
        assert (np.abs(leaves - seed) > 1e-12).any()
    finally:
        t.close()


def test_trainer_prefetch_matches_no_prefetch_first_dispatch(tmp_path):
    """The FIRST dispatch must be identical with the buffer on or off (the
    double buffer only re-times sampling from the second dispatch on):
    same seed → same first-step critic loss."""
    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    losses = []
    for prefetch in (False, True):
        cfg = TrainConfig(
            env="pendulum",
            total_steps=1,
            warmup_steps=32,
            batch_size=16,
            num_envs=2,
            eval_interval=1000,
            checkpoint_interval=1000,
            prefetch=prefetch,
            log_dir=str(tmp_path / f"first_{prefetch}"),
            agent=D4PGConfig(hidden_sizes=(16, 16)),
        )
        cfg = apply_env_preset(cfg)
        t = Trainer(cfg)
        try:
            out = t.train()
            losses.append(float(out["critic_loss"]))
        finally:
            t.close()
    assert losses[0] == pytest.approx(losses[1], abs=1e-6)
