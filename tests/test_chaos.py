"""Chaos harness: deterministic plan parsing/injection, and the recovery
paths each fault class proves (ISSUE-5 acceptance table):

- ``env_raise`` / ``worker_kill`` → supervisor detects, restarts under
  backoff, masks the rows, drops torn windows (fast pool smoke — the
  tier-1 chaos gate);
- ``env_hang`` → the monotonic step deadline fires, the hung worker is
  killed and restarted (fast);
- quarantine after K consecutive failures, all-quarantined → loud error
  (fast);
- the full train-loop integration (wb_stall + env_raise + worker_kill
  under ``--debug-guards``: run completes, zero guard trips, zero leaked
  holds, learner takes every budgeted step) is slow-marked.
"""

import os
import time

import numpy as np
import pytest

from d4pg_tpu.chaos import (
    ChaosEntry,
    ChaosInjector,
    ChaosPlan,
    truncate_checkpoint_step,
)

gym = pytest.importorskip("gymnasium")

ENV = "Pendulum-v1"


# ------------------------------------------------------------------ the plan
def test_plan_parse_full_syntax():
    p = ChaosPlan.parse(
        "seed=7; env_raise@40 ; env_hang@60:30#0, worker_kill@12#1;"
        "ckpt_truncate@1;wb_stall@3:0.5;sock_reset@5"
    )
    assert p.seed == 7
    sites = [e.site for e in p.entries]
    assert sites == [
        "env_raise", "env_hang", "worker_kill", "ckpt_truncate",
        "wb_stall", "sock_reset",
    ]
    assert p.entries[1] == ChaosEntry("env_hang", 60, 30.0, 0)
    assert p.entries[2] == ChaosEntry("worker_kill", 12, None, 1)


def test_plan_parse_multitenant_sites_and_string_label():
    """tenant_flood's ``:arg`` is a string LABEL (the tenant name), not a
    float; the other new sites keep numeric/absent args. str() roundtrips
    the label form (the injector's fired-entry logging)."""
    p = ChaosPlan.parse(
        "tenant_flood@30:bulky;policy_skew@40;scaledown_during_canary@3"
    )
    flood = p.entries[0]
    assert flood == ChaosEntry("tenant_flood", 30, None, None, "bulky")
    assert flood.arg is None and flood.label == "bulky"
    assert str(flood) == "tenant_flood@30:bulky"
    assert p.entries[1] == ChaosEntry("policy_skew", 40)
    assert p.entries[2] == ChaosEntry("scaledown_during_canary", 3)
    # a numeric-looking label on a LABEL site stays a string
    assert ChaosPlan.parse("tenant_flood@1:42").entries[0].label == "42"


@pytest.mark.parametrize(
    "bad", ["boom@3", "env_raise@zero", "env_raise@0", "env_raise", "@3",
            "policy_skew@2:notanumber"]
)
def test_plan_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        ChaosPlan.parse(bad)


def test_plan_parse_rejects_duplicate_site_count():
    """The injector keys on (site, count): a duplicate would silently
    shadow one planned fault — the parse refuses instead."""
    with pytest.raises(ValueError, match="duplicate"):
        ChaosPlan.parse("worker_kill@5#0;worker_kill@5#1")
    # same count at DIFFERENT sites is fine
    ChaosPlan.parse("worker_kill@5#0;env_raise@5#1")


def test_resolve_actors_deterministic_and_bounded():
    p = ChaosPlan.parse("seed=7;env_raise@40;env_hang@9#1")
    r1, r2 = p.resolve_actors(4), p.resolve_actors(4)
    assert r1 == r2  # resolution is a pure function of (seed, count)
    assert r1.entries[0].actor == (7 + 40) % 4
    assert r1.entries[1].actor == 1  # explicit actor untouched
    with pytest.raises(ValueError, match="targets actor"):
        ChaosPlan.parse("env_raise@4#9").resolve_actors(2)


def test_worker_entries_ship_only_that_workers_faults():
    p = ChaosPlan.parse("env_raise@4#0;env_hang@6:2#1;worker_kill@2#0")
    assert p.worker_entries(0) == (("env_raise", 4, None),)
    assert p.worker_entries(1) == (("env_hang", 6, 2.0),)  # kill is parent-side


def test_injector_fires_each_entry_exactly_once():
    inj = ChaosInjector(ChaosPlan.parse("wb_stall@3;wb_stall@5:0.1"))
    fired = [inj.tick("wb_stall") for _ in range(8)]
    hits = [(i + 1) for i, e in enumerate(fired) if e is not None]
    assert hits == [3, 5]
    assert inj.injections_total == 2
    assert inj.summary() == {"chaos_injections": 2, "chaos_pending": 0}
    assert inj.tick("sock_reset") is None  # foreign sites never misfire


def test_truncate_checkpoint_step_halves_largest_file(tmp_path):
    d = tmp_path / "step"
    (d / "sub").mkdir(parents=True)
    (d / "small.bin").write_bytes(b"x" * 10)
    (d / "sub" / "big.bin").write_bytes(b"y" * 1000)
    victim = truncate_checkpoint_step(str(d))
    assert victim.endswith("big.bin")
    assert os.path.getsize(victim) == 500
    assert os.path.getsize(d / "small.bin") == 10
    assert truncate_checkpoint_step(str(tmp_path / "empty")) is None


# --------------------------------------------------- fast pool chaos smoke
def _drive(pool, steps, sleep_s=0.02, act_dim=1):
    """Random-action stepping loop collecting supervision outcomes."""
    rng = np.random.default_rng(0)
    masked, dropped = 0, []
    for _ in range(steps):
        a = rng.uniform(-1, 1, (pool.num_actors, act_dim)).astype(np.float32)
        pool.step(a)
        if not pool.stepped_mask.all():
            masked += 1
        dropped += pool.take_dropped()
        time.sleep(sleep_s)
    return masked, dropped


def test_chaos_smoke_worker_crash_and_kill_recover():
    """The tier-1 chaos gate: an env exception and a SIGKILL both surface
    as supervised failures — the pool masks the rows, drops the torn
    windows, restarts both workers, quarantines neither, and keeps
    stepping (no hang, no batch-shape change)."""
    from d4pg_tpu.runtime.actor_pool import HostActorPool

    inj = ChaosInjector(ChaosPlan.parse("seed=0;env_raise@3#0;worker_kill@6#1"))
    pool = HostActorPool(
        ENV, 2, max_episode_steps=50, seed=0, start_method="fork",
        step_timeout_s=10.0, max_worker_failures=3, chaos=inj,
    )
    try:
        obs = pool.reset_all(seed=0)
        assert obs.shape == (2, 3)
        masked, dropped = _drive(pool, 40)
        assert inj.injections_total == 1  # worker_kill (env_raise is in-child)
        assert pool.failures_total >= 2  # one crash + one kill, both detected
        assert sorted(set(dropped)) == [0, 1]  # torn windows surfaced
        assert masked >= 2  # rows were masked while workers were down
        assert pool.restarts_total >= 2
        assert pool.num_quarantined() == 0
        # both workers rejoined: a late step is full-width again
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            _drive(pool, 1)
            if pool.stepped_mask.all():
                break
        assert pool.stepped_mask.all(), "workers never rejoined the batch"
    finally:
        pool.close()


def test_chaos_env_hang_hits_step_deadline():
    """A hung env must not wedge the parent in conn.recv forever (the old
    behavior): the monotonic step deadline declares the worker hung,
    SIGKILLs it, and restarts it."""
    from d4pg_tpu.runtime.actor_pool import HostActorPool

    inj = ChaosInjector(ChaosPlan.parse("env_hang@2:600#0"))
    pool = HostActorPool(
        ENV, 2, max_episode_steps=50, seed=0, start_method="fork",
        step_timeout_s=1.5, max_worker_failures=3, chaos=inj,
    )
    try:
        pool.reset_all(seed=0)
        t0 = time.monotonic()
        masked, dropped = _drive(pool, 3, sleep_s=0.0)
        assert time.monotonic() - t0 < 10  # bounded, not a 600 s hang
        assert pool.failures_total == 1 and dropped == [0]
        assert any(
            "timeout" in e["detail"]
            for e in pool.events
            if e["event"] == "worker_failed"
        )
    finally:
        pool.close()


def test_quarantine_after_k_consecutive_failures_masks_forever():
    from d4pg_tpu.runtime.actor_pool import HostActorPool

    inj = ChaosInjector(ChaosPlan.parse("env_raise@2#0"))
    pool = HostActorPool(
        ENV, 2, max_episode_steps=50, seed=0, start_method="fork",
        step_timeout_s=10.0, max_worker_failures=1, chaos=inj,
    )
    try:
        pool.reset_all(seed=0)
        _drive(pool, 6, sleep_s=0.0)
        assert pool.num_quarantined() == 1
        assert pool.restarts_total == 0  # quarantined before any restart
        assert any(e["event"] == "worker_quarantine" for e in pool.events)
        # the survivor keeps stepping; the quarantined row stays masked
        _drive(pool, 2, sleep_s=0.0)
        assert bool(pool.stepped_mask[1]) and not bool(pool.stepped_mask[0])
    finally:
        pool.close()


def test_all_quarantined_raises_instead_of_spinning():
    from d4pg_tpu.runtime.actor_pool import HostActorPool

    inj = ChaosInjector(ChaosPlan.parse("env_raise@2#0"))
    pool = HostActorPool(
        ENV, 1, max_episode_steps=50, seed=0, start_method="fork",
        step_timeout_s=10.0, max_worker_failures=1, chaos=inj,
    )
    try:
        pool.reset_all(seed=0)
        with pytest.raises(RuntimeError, match="quarantined"):
            _drive(pool, 6, sleep_s=0.0)
    finally:
        pool.close()


def test_pool_eval_excludes_torn_episodes():
    """An eval worker failing mid-episode must not average rewards from
    two different episodes (or frozen zeros) into keep-best: the torn
    episode is excluded from the eval stats."""
    from types import SimpleNamespace

    from d4pg_tpu.runtime.trainer import Trainer

    n = 3

    class FakePool:
        num_actors = n

        def __init__(self):
            self.t = 0
            self.stepped_mask = np.ones(n, bool)

        def reset_all(self):
            return np.zeros((n, 2), np.float32)

        def take_dropped(self):
            return []

        def step(self, a):
            self.t += 1
            self.stepped_mask = np.ones(n, bool)
            r = np.ones(n, np.float32)
            term = np.zeros(n, bool)
            if self.t == 2:  # worker 0 dies mid-episode; row masked
                self.stepped_mask[0] = False
                r[0] = 0.0
            if self.t >= 4:
                term[:] = True
            z = np.zeros((n, 2), np.float32)
            f = np.zeros(n, bool)
            return z, r, term, f, z, f, f

    fake = SimpleNamespace(
        config=SimpleNamespace(eval_episodes=n, max_episode_steps=6),
        _eval_pool=FakePool(),
        _get_eval_act=lambda: (lambda p, o: np.zeros((n, 1), np.float32)),
        _eval_params=lambda: None,
        _norm_obs=lambda x: x,
    )
    out = Trainer._pool_eval(fake)
    # survivors accumulated r=1 for 4 steps; the torn episode (which would
    # have contributed ~1.0) is excluded entirely
    assert out["eval_return_mean"] == 4.0
    assert "success_rate" not in out

    class AllDeadPool(FakePool):
        def step(self, a):
            out = super().step(a)
            self.stepped_mask[:] = False
            return out

    fake_dead = SimpleNamespace(
        config=SimpleNamespace(eval_episodes=n, max_episode_steps=6),
        _eval_pool=AllDeadPool(),
        _get_eval_act=lambda: (lambda p, o: np.zeros((n, 1), np.float32)),
        _eval_params=lambda: None,
        _norm_obs=lambda x: x,
    )
    with pytest.raises(RuntimeError, match="every eval episode"):
        Trainer._pool_eval(fake_dead)


# ----------------------------------------------------- train-loop integration
@pytest.mark.slow
def test_chaos_train_run_completes_with_guards_green(tmp_path):
    """The acceptance gate, in-process: a short pool training run under
    env_raise + worker_kill + wb_stall with --debug-guards completes
    every budgeted learner step, reports the injections in its metrics
    rows, and ends with zero ledger trips and zero leaked holds."""
    import json

    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = apply_env_preset(
        TrainConfig(
            env=ENV,
            num_envs=2,
            total_steps=6,
            warmup_steps=40,
            batch_size=16,
            replay_capacity=2_000,
            eval_interval=6,
            eval_episodes=1,
            max_episode_steps=20,
            checkpoint_interval=100_000,
            pool_start_method="fork",
            pool_step_timeout_s=10.0,
            async_priority_writeback=True,
            debug_guards=True,
            chaos="seed=3;env_raise@5#0;worker_kill@9#1;wb_stall@1:0.2",
            log_dir=str(tmp_path / "run"),
        )
    )
    t = Trainer(cfg)
    try:
        out = t.train()
        assert t.grad_steps == 6  # the learner took every budgeted step
        assert np.isfinite(out["critic_loss"])
        assert t.pool.failures_total >= 2 and t.pool.restarts_total >= 1
        assert t._chaos.injections_total >= 2  # worker_kill + wb_stall fired
    finally:
        t.close()
    stats = t._ledger.stats()
    assert stats["trips"] == 0, stats
    assert stats["active_holds"] == 0, stats  # no leaked holds after close
    with open(tmp_path / "run" / "metrics.jsonl") as f:
        rows = [json.loads(l) for l in f]
    assert any("chaos_injections" in r for r in rows)
    assert any(r.get("pool_worker_restarts", 0) >= 1 for r in rows)
