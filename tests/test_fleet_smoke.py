"""Tier-1 fleet smoke: the 2-process collection fleet through the real
CLIs (``scripts/fleet_smoke.sh``) — train.py --fleet-listen with NO local
collection, a remote actor host streaming real windows, a bundle
hot-swap mid-run, and a SIGTERM drain with every emitted window
accounted for.

This is THE end-to-end smoke for the fleet subsystem (conftest fast-tier
policy): everything else fleet-related tests layers in-process
(``tests/test_fleet.py``); only this one proves the shipped commands
compose.
"""

import os
import subprocess
import sys

from conftest import clean_cpu_env


def test_fleet_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["FLEET_SMOKE_DIR"] = str(tmp_path / "run")
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "fleet_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=840,
        env=env,
        cwd=repo,
    )
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "FLEET_SMOKE_COUNTERS_OK" in p.stdout, out[-4000:]
    assert "FLEET_SMOKE_OK" in p.stdout, out[-4000:]
    # the published bundle is a real directory artifact the actor swapped
    assert os.path.exists(str(tmp_path / "run" / "bundle" / "bundle.json"))


if __name__ == "__main__":
    sys.exit(0)
