"""Capability-seam tests: the negotiation table, the committed matrix,
the single validation call site, and the fleet HELLO negotiation
(ISSUE 13)."""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from d4pg_tpu.config import TrainConfig
from d4pg_tpu.fleet import wire
from d4pg_tpu.fleet.ingest import IngestServer
from d4pg_tpu.ops.obs_norm import RunningObsNorm
from d4pg_tpu.replay import source
from d4pg_tpu.replay.uniform import ReplayBuffer
from d4pg_tpu.serve import protocol

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "benchmarks", "composition_matrix.json")


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ----------------------------------------------------------- rule table
def test_every_cell_has_verdict_and_reasons():
    cells = source.composition_matrix()
    assert len(cells) == len(source.SCENARIOS) * len(source.PLACEMENTS)
    for c in cells:
        assert c["verdict"] in ("pass", "negotiated", "gap")
        if c["verdict"] == "gap":
            assert c["gaps"] and all(
                g["code"] and g["message"] for g in c["gaps"]
            )
        if c["verdict"] == "negotiated":
            assert c["actions"]


def test_issue13_cells_are_open():
    """The cells the old refusal matrix closed are now pass at host
    placement: fleet × {pixel, obs-norm, HER, HER+obs-norm}."""
    by = {(c["scenario"], c["placement"]): c["verdict"]
          for c in source.composition_matrix()}
    for scen in ("fleet_pixel", "fleet_obs_norm", "fleet_her",
                 "fleet_her_obs_norm", "fleet_bf16_wire"):
        assert by[(scen, "host")] == "pass", scen


def test_device_per_is_a_pass_and_hybrid_is_legacy():
    """ISSUE 14: device placement composes with PER outright (the
    priority structure is device-resident) — the old
    per_downgraded_uniform action is gone — and hybrid re-verdicts as
    the DECLARED legacy host-tree placement."""
    n = source.negotiate(source.RequestedCaps(placement="device"))
    assert n.verdict == "pass"
    assert n.actions == ()
    n_dp = source.negotiate(source.RequestedCaps(placement="device", dp=8))
    assert n_dp.verdict == "pass"
    n_hyb = source.negotiate(source.RequestedCaps(placement="hybrid"))
    assert n_hyb.verdict == "negotiated"
    assert "hybrid_legacy_host_tree" in n_hyb.actions


def test_committed_artifact_is_fresh_and_schema_clean():
    """Tier-1 regeneration smoke: the committed artifact equals a fresh
    evaluation of the rule table, and the schema gate passes it."""
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    try:
        import composition_matrix as gen
    finally:
        sys.path.pop(0)
    with open(ARTIFACT) as f:
        committed = json.load(f)
    assert committed == gen.build(), (
        "benchmarks/composition_matrix.json is stale — regenerate with "
        "`python benchmarks/composition_matrix.py`"
    )
    from tools.d4pglint.schema_check import check_composition_matrix

    assert check_composition_matrix(ARTIFACT) == []


def test_schema_gate_refuses_undeclared_refusal(tmp_path):
    """A gap cell stripped of its machine-readable reasons — an
    UNDECLARED refusal — must not be committable."""
    from tools.d4pglint.schema_check import check_composition_matrix

    with open(ARTIFACT) as f:
        doc = json.load(f)
    gap_cells = [c for c in doc["cells"] if c["verdict"] == "gap"]
    gap_cells[0].pop("gaps")
    p = tmp_path / "composition_matrix.json"
    p.write_text(json.dumps(doc))
    errs = check_composition_matrix(str(p))
    assert any("undeclared refusals" in e for e in errs)
    # and a stale-cell drift is caught too
    doc["cells"][0]["verdict"] = "gap"
    doc["cells"][0]["gaps"] = [{"code": "x", "message": "y"}]
    p.write_text(json.dumps(doc))
    errs = check_composition_matrix(str(p))
    assert any("stale" in e for e in errs)


# -------------------------------------------------- single call site
def test_trainer_refusal_text_is_the_seam_text():
    """The Trainer and the CLI raise the seam's exact message — the
    drift the satellite kills. Checked WITHOUT building a Trainer: both
    call sites call validate_train_config, pinned here on a gap config."""
    cfg = TrainConfig(replay_placement="hybrid", prioritized=False)
    n = source.negotiate(source.from_train_config(cfg))
    with pytest.raises(ValueError) as ei:
        source.validate_train_config(cfg)
    assert str(ei.value) == n.message()
    assert "hybrid is the PER mode" in str(ei.value)


def test_cli_and_constructor_share_on_device_rules():
    cfg = TrainConfig(fleet_listen=5000, obs_norm=True, num_envs=0)
    with pytest.raises(ValueError) as ei:
        source.validate_train_config(cfg, on_device=True)
    msg = str(ei.value)
    assert "--fleet-listen feeds the HOST replay buffer" in msg
    assert "--obs-norm is a host data-boundary feature" in msg


def test_mixed_mode_obs_norm_single_writer_gap():
    cfg = TrainConfig(fleet_listen=5000, obs_norm=True, num_envs=2)
    with pytest.raises(ValueError, match="exactly one"):
        source.validate_train_config(cfg)
    # fleet-only is the open cell
    ok = TrainConfig(fleet_listen=5000, obs_norm=True, num_envs=0)
    assert source.validate_train_config(
        ok, is_jax_env=False
    ).verdict == "pass"


# ------------------------------------------------- fleet HELLO negotiation
OBS, ACT, NSTEP, GAMMA = 5, 2, 3, 0.97


def _start(caps=None, obs_norm=None, **kw):
    buf = ReplayBuffer(256, OBS, ACT)
    srv = IngestServer(
        buf, obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
        host="127.0.0.1", port=0, caps=caps, obs_norm=obs_norm, **kw,
    ).start()
    return srv, buf


def _hello(srv, caps=None, generation=0):
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    s.settimeout(5)
    protocol.write_frame(
        s, protocol.HELLO, 1,
        wire.encode_hello(
            actor_id="t", env="e", obs_dim=OBS, action_dim=ACT,
            n_step=NSTEP, gamma=GAMMA, generation=generation, caps=caps,
        ),
    )
    return s, protocol.read_frame(s)


def test_legacy_hello_gets_byte_identical_v1_reply():
    """A caps-less HELLO against a default-caps server: HELLO_OK payload
    bytes are EXACTLY the pre-ISSUE-13 encoding (no caps key)."""
    srv, _ = _start()
    try:
        s, (t, _r, payload) = _hello(srv)
        assert t == protocol.HELLO_OK
        want = wire.encode_hello_ok(
            generation=0, max_windows=srv.max_windows,
            max_inflight=srv.max_inflight,
        )
        assert payload == want
        assert "caps" not in json.loads(payload.decode())
        s.close()
    finally:
        srv.close()


def test_capability_mismatch_refused_with_structured_reason():
    """A HER-requiring learner refuses a non-HER actor (and a legacy
    one) with machine-readable gap codes, never a silent accept."""
    srv, _ = _start(caps={"obs_mode": "f32", "her": True, "obs_norm": False})
    try:
        # legacy actor: no caps at all
        s, (t, _r, payload) = _hello(srv)
        assert t == protocol.ERROR
        doc = wire.decode_refusal(payload)
        assert doc and [g["code"] for g in doc["gaps"]] == ["her_required"]
        assert "handshake refused" in doc["message"]
        s.close()
        # new actor, explicitly without --her
        s, (t, _r, payload) = _hello(
            srv, caps={"obs_modes": ["f32"], "her": False, "obs_norm": False}
        )
        doc = wire.decode_refusal(payload)
        assert t == protocol.ERROR and doc
        assert [g["code"] for g in doc["gaps"]] == ["her_required"]
        s.close()
        assert srv.counters()["handshake_refusals"] == 2
        # matching actor: accepted, caps echoed
        s, (t, _r, payload) = _hello(
            srv, caps={"obs_modes": ["f32"], "her": True, "obs_norm": False}
        )
        assert t == protocol.HELLO_OK
        ok = wire.decode_hello_ok(payload)
        assert ok["caps"] == {"obs_mode": "f32", "her": True,
                              "obs_norm": False, "variant": 0}
        s.close()
    finally:
        srv.close()


def test_u8_negotiation_and_v1_frame_rejected_on_u8_ingest():
    srv, _ = _start(caps={"obs_mode": "u8", "her": False, "obs_norm": False})
    try:
        s, (t, _r, payload) = _hello(
            srv,
            caps={"obs_modes": ["f32", "u8", "bf16"], "her": False,
                  "obs_norm": False},
        )
        assert t == protocol.HELLO_OK
        assert wire.decode_hello_ok(payload)["caps"]["obs_mode"] == "u8"
        # a v1 WINDOWS frame on the u8 ingest: protocol error, ERROR+close
        rng = np.random.default_rng(0)
        protocol.write_frame(
            s, protocol.WINDOWS, 2,
            wire.encode_windows(
                0, rng.random((2, OBS), np.float32),
                rng.random((2, ACT), np.float32),
                rng.random(2).astype(np.float32),
                rng.random((2, OBS), np.float32),
                rng.random(2).astype(np.float32),
            ),
        )
        t, _r, payload = protocol.read_frame(s)
        assert t == protocol.ERROR and b"WINDOWS2" in payload
        assert protocol.read_frame(s) is None
        s.close()
        assert _wait(lambda: srv.counters()["protocol_errors"] == 1)
    finally:
        srv.close()


def _send_w2(s, req_id, gen, stats_gen, relabeled, rows=3, fill=None):
    rng = np.random.default_rng(req_id)
    obs = (
        np.full((rows, OBS), fill, np.float32)
        if fill is not None else rng.random((rows, OBS), np.float32)
    )
    protocol.write_frame(
        s, protocol.WINDOWS2, req_id,
        wire.encode_windows2(
            gen, stats_gen, "f32", relabeled,
            obs, rng.random((rows, ACT), np.float32),
            rng.random(rows).astype(np.float32),
            rng.random((rows, OBS), np.float32),
            rng.random(rows).astype(np.float32),
        ),
    )
    return protocol.read_frame(s)


def test_stale_stats_dropped_and_counted_fold_originals_only():
    """Windows under stale obs-norm statistics are counted + dropped
    like stale-generation ones; accepted ORIGINAL windows fold the
    statistics, relabeled ones never do."""
    norm = RunningObsNorm(OBS)
    srv, buf = _start(
        caps={"obs_mode": "f32", "her": True, "obs_norm": True},
        obs_norm=norm, max_gen_lag=1,
    )
    try:
        srv.set_generation(5)
        s, (t, _r, payload) = _hello(
            srv, caps={"obs_modes": ["f32"], "her": True, "obs_norm": True},
            generation=5,
        )
        assert t == protocol.HELLO_OK
        assert wire.decode_hello_ok(payload)["stats_generation"] == 5
        # stats_gen 3 < 5 - 1: stale stats (gen itself is fresh)
        t, _r, p = _send_w2(s, 2, gen=5, stats_gen=3, relabeled=False)
        assert t == protocol.WINDOWS_OK
        assert wire.decode_windows_ok(p) == (0, 3)
        c = srv.counters()
        assert c["windows_dropped_stale_stats"] == 3
        assert c["windows_dropped_stale_gen"] == 0
        assert norm.count == 0  # dropped windows never fold
        # fresh original window: accepted AND folded
        t, _r, p = _send_w2(s, 3, gen=5, stats_gen=5, relabeled=False)
        assert wire.decode_windows_ok(p) == (3, 0)
        assert _wait(lambda: srv.counters()["windows_ingested"] == 3)
        assert norm.count == 3
        # relabeled window: accepted, NOT folded
        t, _r, p = _send_w2(s, 4, gen=5, stats_gen=5, relabeled=True)
        assert wire.decode_windows_ok(p) == (3, 0)
        assert _wait(lambda: srv.counters()["windows_ingested"] == 6)
        assert norm.count == 3
        assert len(buf) == 6
        s.close()
    finally:
        srv.close()


# -------------------------------------------------- tier-1 clock guard
def test_fast_tier_additions_fit_budget():
    """ISSUE-13 satellite: the new fast-tier suites must stay lean. The
    parity + composition suites (this file and
    test_data_plane_parity.py) assert their own combined budget by
    re-running the parity suite in a subprocess and timing it — well
    under the ~300 s of tier-1 headroom the ISSUE names (the heavy
    400-step compositions are slow-marked)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         os.path.join(REPO, "tests", "test_data_plane_parity.py")],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=120,
    )
    dt = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert dt < 60.0, f"parity suite took {dt:.1f}s — trim it"
