"""Flywheel units (ISSUE 18): the FEEDBACK frame codec and its version
floor, the WINDOWS2 behavior-log-prob column, the mirror spool, the
off-policy IS gate's math, the mirror tap's striping + accounting
identity — and the headline parity claim extended to mirrored traffic:
an episode mirrored through MirrorTap → socket → IngestServer leaves
replay content byte-identical to the in-process NStepWriter path.

Everything here is in-process and device-free. The end-to-end loop
(server + tap + learner + sim client) lives in
``tests/test_flywheel_smoke.py`` (scripts/flywheel_smoke.sh); the
closed-loop improvement + gate-blocks-bad-bundle soak in
``scripts/chaos_soak.sh`` leg 10.
"""

import math
import os
import struct
import time

import numpy as np
import pytest

from d4pg_tpu.chaos import ChaosInjector, ChaosPlan
from d4pg_tpu.fleet import wire
from d4pg_tpu.fleet.ingest import IngestServer
from d4pg_tpu.flywheel.gate import evaluate_is_gate, gaussian_log_prob
from d4pg_tpu.flywheel.spool import MirrorSpool, iter_payloads, read_windows
from d4pg_tpu.flywheel.tap import MirrorTap
from d4pg_tpu.replay.nstep_writer import NStepWriter
from d4pg_tpu.replay.source import negotiate_fleet
from d4pg_tpu.replay.uniform import ReplayBuffer
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import ProtocolError

OBS, ACT, NSTEP, GAMMA = 3, 2, 2, 0.99


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _fb(step, *, terminated=False, truncated=False, action=None):
    return dict(
        policy_id="default",
        reward=float(step),
        log_prob=-0.5 * step,
        terminated=terminated,
        truncated=truncated,
        action=(np.full(ACT, 0.1 * step, np.float32)
                if action is None else action),
        next_obs=np.full(OBS, step + 1, np.float32),
    )


# ---------------------------------------------------------- FEEDBACK codec
def test_feedback_roundtrip():
    action = np.array([0.25, -0.75], np.float32)
    next_obs = np.array([1.0, 2.0, 3.0], np.float32)
    payload = protocol.encode_feedback(
        1.5, action, next_obs, log_prob=-0.625, terminated=True,
        policy_id="pol_b",
    )
    fb = protocol.decode_feedback(payload)
    assert fb["policy_id"] == "pol_b"
    assert fb["reward"] == 1.5
    assert abs(fb["log_prob"] - -0.625) < 1e-6
    assert fb["terminated"] and not fb["truncated"]
    np.testing.assert_array_equal(fb["action"], action)
    np.testing.assert_array_equal(fb["next_obs"], next_obs)
    # both episode bits, independently
    fb2 = protocol.decode_feedback(
        protocol.encode_feedback(0.0, action, next_obs, truncated=True)
    )
    assert fb2["truncated"] and not fb2["terminated"]
    assert fb2["policy_id"] == protocol.DEFAULT_POLICY


def test_feedback_malformed():
    action = np.zeros(ACT, np.float32)
    next_obs = np.zeros(OBS, np.float32)
    good = protocol.encode_feedback(0.0, action, next_obs)
    with pytest.raises(ProtocolError):
        protocol.decode_feedback(good[: protocol._FEEDBACK_HEAD.size - 1])
    with pytest.raises(ProtocolError):
        protocol.decode_feedback(good[:-2])  # next_obs not a f32 multiple
    with pytest.raises(ProtocolError):
        # action block truncated away entirely
        protocol.decode_feedback(good[: protocol._FEEDBACK_HEAD.size + 3])
    with pytest.raises(ProtocolError):
        protocol.encode_feedback(0.0, np.zeros((2, 2), np.float32), next_obs)
    with pytest.raises(ProtocolError):
        protocol.encode_feedback(0.0, action, next_obs, policy_id="x" * 300)


def test_feedback_rides_version2_v1_frames_pinned():
    """The backward-compat satellite: FEEDBACK/FEEDBACK_OK stamp frame
    version 2, while the v1 sublanguage — ACT out, ACT_OK back, WINDOWS
    up — stays byte-for-byte what a PR-8-era peer speaks, BOTH
    directions, pinned against hand-packed golden bytes."""

    class Sink:
        def __init__(self):
            self.data = b""

        def sendall(self, b):
            self.data += b

    def framed(msg_type, req_id, payload):
        s = Sink()
        protocol.write_frame(s, msg_type, req_id, payload)
        return s.data

    # client -> server request path, v1
    obs = np.arange(OBS, dtype=np.float32)
    act_payload = protocol.encode_act(obs, 500)
    golden_act = (
        protocol.HEADER.pack(b"D4", 1, protocol.ACT, 7, len(act_payload))
        + struct.pack("<I", 500) + obs.tobytes()
    )
    assert framed(protocol.ACT, 7, act_payload) == golden_act
    # server -> client reply path, v1
    action = np.array([0.5, -0.5], np.float32)
    golden_ok = (
        protocol.HEADER.pack(b"D4", 1, protocol.ACT_OK, 7, 4 * ACT)
        + action.tobytes()
    )
    assert framed(protocol.ACT_OK, 7, protocol.encode_action(action)) == \
        golden_ok
    # actor -> ingest v1 WINDOWS: header byte stays 1
    w = wire.encode_windows(
        0, np.zeros((1, OBS), np.float32), np.zeros((1, ACT), np.float32),
        np.zeros(1, np.float32), np.zeros((1, OBS), np.float32),
        np.zeros(1, np.float32),
    )
    assert framed(protocol.WINDOWS, 1, w)[:4] == b"D4" + bytes(
        [1, protocol.WINDOWS]
    )
    # the flywheel frames ride version 2
    fb = protocol.encode_feedback(
        0.0, np.zeros(ACT, np.float32), np.zeros(OBS, np.float32)
    )
    assert framed(protocol.FEEDBACK, 3, fb)[:4] == b"D4" + bytes(
        [2, protocol.FEEDBACK]
    )
    assert framed(protocol.FEEDBACK_OK, 3, b"")[:4] == b"D4" + bytes(
        [2, protocol.FEEDBACK_OK]
    )


# ------------------------------------------------- WINDOWS2 logprob column
def _cols(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(
        obs=rng.standard_normal((n, OBS)).astype(np.float32),
        action=rng.standard_normal((n, ACT)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, OBS)).astype(np.float32),
        discount=rng.random(n).astype(np.float32),
    )


def test_windows2_logprob_column_roundtrip_and_plain_unchanged():
    cols = _cols(4)
    lp = np.linspace(-3, -1, 4).astype(np.float32)
    with_lp = wire.encode_windows2(5, 6, "f32", False, logprob=lp, **cols)
    gen, sg, mode, relab, out = wire.decode_windows2(with_lp, OBS, ACT)
    assert (gen, sg, mode, relab) == (5, 6, "f32", False)
    np.testing.assert_array_equal(out["logprob"], lp)
    np.testing.assert_array_equal(out["obs"], cols["obs"])
    np.testing.assert_array_equal(out["discount"], cols["discount"])
    # plain frame: byte-identical to the pre-flywheel wire (flags 0, no
    # trailing column), and the decode has no logprob key
    plain = wire.encode_windows2(5, 6, "f32", False, **cols)
    golden = (
        wire._WINDOWS2_HEAD.pack(5, 6, 4, wire.OBS_MODE_IDS["f32"], 0, 0)
        + cols["obs"].tobytes() + cols["action"].tobytes()
        + cols["reward"].tobytes() + cols["next_obs"].tobytes()
        + cols["discount"].tobytes()
    )
    assert plain == golden
    assert with_lp == golden[:12] + with_lp[12:16] + golden[16:] + lp.tobytes()
    _, _, _, _, out2 = wire.decode_windows2(plain, OBS, ACT)
    assert "logprob" not in out2
    # truncated logprob block dies whole
    with pytest.raises(ProtocolError, match="declares"):
        wire.decode_windows2(with_lp[:-4], OBS, ACT)


def test_hello_source_cap_and_negotiation():
    from d4pg_tpu.replay.source import LEGACY_ACTOR_CAPS

    learner = {"obs_mode": "f32", "her": False, "obs_norm": False,
               "variant": 0}
    # the mirror tap's HELLO declares source=mirror; it survives the
    # HELLO codec roundtrip and negotiation hands it through
    hello = wire.decode_hello(wire.encode_hello(
        actor_id="m", env="e", obs_dim=OBS, action_dim=ACT,
        n_step=NSTEP, gamma=GAMMA, generation=0,
        caps={"wire": 2, "obs_modes": ["f32"], "her": False,
              "obs_norm": False, "variant": 0, "source": "mirror"},
    ))
    assert hello["caps"]["source"] == "mirror"
    chosen, gaps = negotiate_fleet(learner, hello["caps"])
    assert gaps == () and chosen["source"] == "mirror"
    # caps-less v1 actor — and a caps vector without the key — both
    # negotiate as plain actors
    chosen, gaps = negotiate_fleet(learner, LEGACY_ACTOR_CAPS)
    assert gaps == () and chosen["source"] == "actor"
    chosen, gaps = negotiate_fleet(
        learner,
        {"obs_modes": ["f32"], "her": False, "obs_norm": False,
         "variant": 0},
    )
    assert gaps == () and chosen["source"] == "actor"


# ------------------------------------------------------------------- spool
def test_spool_roundtrip_rotation_torn_tail(tmp_path):
    root = str(tmp_path / "spool")
    sp = MirrorSpool(root, segment_bytes=256, max_segments=2)
    payloads = [bytes([i]) * (40 + i) for i in range(12)]
    for p in payloads:
        sp.append(p)
    sp.close()
    kept = list(iter_payloads(root))
    assert 0 < len(kept) < len(payloads)
    assert kept == payloads[-len(kept):]  # oldest segments rotated away
    # torn tail: a half-written record is skipped, everything before reads
    segs = sorted(
        f for f in os.listdir(root) if f.startswith("mirror-")
    )
    last = os.path.join(root, segs[-1])
    with open(last, "ab") as f:
        f.write(struct.pack("<I", 9999) + b"short")
    assert list(iter_payloads(root)) == kept


def test_read_windows_filters(tmp_path):
    root = str(tmp_path / "spool")
    sp = MirrorSpool(root)
    cols = _cols(3, seed=1)
    lp = np.float32([-1, -2, -3])
    sp.append(wire.encode_windows2(1, 1, "f32", False, **cols))  # no logprob
    sp.append(wire.encode_windows2(2, 2, "f32", False, logprob=lp, **cols))
    sp.append(wire.encode_windows2(7, 7, "f32", False, logprob=lp, **cols))
    sp.append(b"not a frame")  # foreign record: skipped, never raises
    sp.close()
    out, n = read_windows(root, OBS, ACT)
    assert n == 6  # the logprob-less frame is skipped
    out, n = read_windows(root, OBS, ACT, min_generation=3)
    assert n == 3
    out, n = read_windows(root, OBS, ACT, max_windows=2)
    assert n == 2 and len(out["logprob"]) == 2
    assert read_windows(str(tmp_path / "missing"), OBS, ACT) == ({}, 0)


# ---------------------------------------------------------------- IS gate
def test_gaussian_log_prob_matches_closed_form():
    a = np.array([[0.3, -0.1]])
    m = np.array([[0.1, 0.2]])
    sigma = 0.25
    want = sum(
        -((a[0, i] - m[0, i]) ** 2) / (2 * sigma**2)
        - math.log(sigma) - 0.5 * math.log(2 * math.pi)
        for i in range(2)
    )
    got = gaussian_log_prob(a, m, sigma)
    assert got.shape == (1,) and abs(float(got[0]) - want) < 1e-12


def _gate_cols(n, behavior_mean, sigma, reward_of, seed=0):
    """Windows logged by a behavior policy acting N(behavior_mean, σ²)."""
    rng = np.random.default_rng(seed)
    obs = rng.standard_normal((n, OBS)).astype(np.float32)
    mean = behavior_mean(obs)
    action = (mean + rng.normal(0, sigma, (n, ACT))).astype(np.float32)
    return dict(
        obs=obs, action=action,
        reward=reward_of(obs, action).astype(np.float32),
        logprob=gaussian_log_prob(action, mean, sigma).astype(np.float32),
    )


class _Lin:
    """Deterministic linear policy μ(s) = s @ W — the NumpyPolicy shape
    the gate needs (act + dims)."""

    obs_dim, action_dim = OBS, ACT

    def __init__(self, w):
        self.w = np.asarray(w, np.float64)

    def act(self, obs):
        return np.asarray(obs, np.float64) @ self.w


class _Const:
    """Constant-action candidate: acts nowhere near anything the
    behavior policy served, so its importance weights collapse onto
    whichever single window is least unlike it."""

    obs_dim, action_dim = OBS, ACT

    def __init__(self, value):
        self.value = float(value)

    def act(self, obs):
        return np.full((len(obs), ACT), self.value)


def test_gate_pass_block_starve_ess():
    sigma = 0.3
    w_good = np.zeros((OBS, ACT))
    behavior = _Lin(w_good)
    # reward: high when the action is near 0 (what behavior does)
    reward_of = lambda obs, act: 2.0 - np.sum(act**2, axis=1)  # noqa: E731
    cols = _gate_cols(200, behavior.act, sigma, reward_of, seed=2)
    # candidate == behavior: ρ == 1 everywhere, estimate == mean, passes
    v = evaluate_is_gate(cols, _Lin(w_good), sigma=sigma,
                         min_windows=16, min_ess=4.0, band=0.5)
    assert v["passed"] and v["reason"] == "ok"
    assert abs(v["v_candidate"] - v["v_behavior"]) < 0.2
    assert v["ess"] > 100
    # far-off-distribution candidate: ESS collapses, blocked
    v = evaluate_is_gate(cols, _Const(2.0), sigma=sigma,
                         min_windows=16, min_ess=4.0, band=0.5)
    assert not v["passed"] and "sample size" in v["reason"]
    assert v["ess"] < 4.0
    # pathologically far candidate: EVERY weight underflows — the gate
    # refuses rather than dividing by zero
    v = evaluate_is_gate(cols, _Const(50.0), sigma=sigma,
                         min_windows=16, min_ess=4.0, band=0.5)
    assert not v["passed"] and v["ess"] == 0.0
    # starved gate refuses, never guesses
    few = {k: c[:4] for k, c in cols.items()}
    v = evaluate_is_gate(few, _Lin(w_good), sigma=sigma, min_windows=16)
    assert not v["passed"] and v["reason"].startswith("starved")
    assert evaluate_is_gate({}, _Lin(w_good), sigma=sigma)["passed"] is False
    # near-distribution but WORSE candidate: rewarded region is where
    # behavior acts, candidate drifts away -> estimate drops below band
    v = evaluate_is_gate(
        cols, _Lin(np.full((OBS, ACT), 0.25)), sigma=sigma,
        min_windows=16, min_ess=4.0, band=0.05,
    )
    assert not v["passed"] and "below behavior" in v["reason"]
    for k in ("samples", "sigma", "ess", "v_behavior", "v_candidate",
              "min_windows", "min_ess", "band", "passed", "reason"):
        assert k in v


# -------------------------------------------------------------- mirror tap
def test_tap_striping_identity_and_unpaired(tmp_path):
    tap = MirrorTap(obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                    fraction=0.5, spool=MirrorSpool(str(tmp_path / "sp")))
    try:
        # feedback with no preceding request: counted, never paired
        tap.on_feedback("conn", _fb(0))
        assert tap.counters()["feedback_unpaired"] == 1
        # 8 episodes of 4 steps on one connection: Bresenham at 500‰
        # mirrors exactly every other episode
        for _ep in range(8):
            for step in range(4):
                tap.on_request("conn", np.full(OBS, step, np.float32))
                tap.on_feedback("conn", _fb(step, terminated=step == 3))
        c = tap.counters()
        assert c["episodes_seen"] == 8 and c["episodes_mirrored"] == 4
        assert c["feedback_steps"] == 32
        assert c["windows_built"] == 16  # 4 windows per mirrored episode
    finally:
        tap.close()
    c = tap.counters()
    assert c["windows_built"] == (
        c["windows_acked"] + c["windows_stale"] + c["windows_shed"]
        + c["windows_dropped_chaos"] + c["windows_dropped_link"]
        + c["windows_dropped_full"] + c["pending"]
    )
    # no ingest configured: the spool got everything, the link dropped all
    assert c["windows_dropped_link"] == 16 and c["spool_records"] >= 1
    _, n = read_windows(str(tmp_path / "sp"), OBS, ACT)
    assert n == 16


def test_tap_disconnect_drops_half_built_episode():
    tap = MirrorTap(obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                    fraction=1.0)
    try:
        tap.on_request("c", np.zeros(OBS, np.float32))
        tap.on_feedback("c", _fb(0))  # 1 step < n_step: nothing emitted
        assert tap.counters()["windows_built"] == 0
        tap.on_disconnect("c")
        # stream gone whole: the next feedback on the same key is unpaired
        tap.on_feedback("c", _fb(1))
        c = tap.counters()
        assert c["windows_built"] == 0 and c["feedback_unpaired"] == 1
    finally:
        tap.close()


def test_tap_chaos_mirror_drop_keeps_identity(tmp_path):
    chaos = ChaosInjector(ChaosPlan.parse("mirror_drop@1;mirror_drop@3"))
    tap = MirrorTap(obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                    fraction=1.0, spool=MirrorSpool(str(tmp_path / "sp")),
                    chaos=chaos)
    try:
        for step in range(6):
            tap.on_request("c", np.full(OBS, step, np.float32))
            tap.on_feedback("c", _fb(step, terminated=step == 5))
    finally:
        tap.close()
    c = tap.counters()
    assert c["windows_built"] == 6
    assert c["windows_dropped_chaos"] == 2  # the 1st and 3rd built windows
    assert c["windows_built"] == (
        c["windows_acked"] + c["windows_stale"] + c["windows_shed"]
        + c["windows_dropped_chaos"] + c["windows_dropped_link"]
        + c["windows_dropped_full"] + c["pending"]
    )
    # dropped BEFORE both sinks: the spool holds only the surviving 4
    _, n = read_windows(str(tmp_path / "sp"), OBS, ACT)
    assert n == 4


def test_tap_rejects_bad_fraction():
    with pytest.raises(ValueError):
        MirrorTap(obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                  fraction=1.5)


# ------------------------------------------------- mirrored-replay parity
def _episode_stream(seed, steps):
    rng = np.random.default_rng(seed)
    obs = rng.standard_normal(OBS).astype(np.float32)
    t = 0
    for i in range(steps):
        action = rng.standard_normal(ACT).astype(np.float32)
        reward = float(rng.standard_normal())
        next_obs = rng.standard_normal(OBS).astype(np.float32)
        t += 1
        term = t == 9 and (i // 9) % 2 == 0
        trunc = t == 9 and not term
        yield obs, action, reward, next_obs, term, trunc
        if term or trunc:
            obs = rng.standard_normal(OBS).astype(np.float32)
            t = 0
        else:
            obs = next_obs


def test_mirrored_and_inprocess_replay_content_identical():
    """The parity claim extended to the flywheel: the same episode stream
    through (a) the in-process NStepWriter -> ReplayBuffer path and
    (b) the mirror path — MirrorTap -> WINDOWS2+logprob frame -> socket
    -> IngestServer (source: mirror) -> ReplayBuffer — leaves
    byte-identical replay content, split out on the ingest's per-source
    counters, with the logprob column stripped before storage."""
    buf_local = ReplayBuffer(512, OBS, ACT)
    w_local = NStepWriter(buf_local, NSTEP, GAMMA)
    buf_fleet = ReplayBuffer(512, OBS, ACT)
    srv = IngestServer(buf_fleet, obs_dim=OBS, action_dim=ACT,
                       n_step=NSTEP, gamma=GAMMA, port=0).start()
    tap = MirrorTap(obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                    fraction=1.0, ingest_addr=("127.0.0.1", srv.port))
    try:
        for obs, action, reward, next_obs, term, trunc in \
                _episode_stream(11, 120):
            w_local.add(obs, action, reward, next_obs, term, trunc)
            tap.on_request("c", obs)
            tap.on_feedback("c", dict(
                policy_id="default", reward=reward, log_prob=-1.0,
                terminated=term, truncated=trunc,
                action=action, next_obs=next_obs,
            ))
        emitted = len(buf_local)
        assert emitted > 0
        assert _wait(lambda: len(buf_fleet) == emitted), (
            f"fleet buffer {len(buf_fleet)} != local {emitted}"
        )
        tap.close()
        n = emitted
        np.testing.assert_array_equal(buf_fleet.obs[:n], buf_local.obs[:n])
        np.testing.assert_array_equal(
            buf_fleet.action[:n], buf_local.action[:n]
        )
        np.testing.assert_array_equal(
            buf_fleet.reward[:n], buf_local.reward[:n]
        )
        np.testing.assert_array_equal(
            buf_fleet.next_obs[:n], buf_local.next_obs[:n]
        )
        np.testing.assert_array_equal(
            buf_fleet.discount[:n], buf_local.discount[:n]
        )
        c = tap.counters()
        assert c["windows_acked"] == emitted
        snap = srv.counters()
        assert snap["windows_from_mirror"] == emitted
        assert snap["windows_from_actors"] == 0
        assert snap["windows_ingested"] == (
            snap["windows_from_mirror"] + snap["windows_from_actors"]
        )
    finally:
        tap.close()
        srv.close()
