"""Tier-1 league smoke: the PBT controller lifecycle through the real
CLI (``scripts/league_smoke.sh``) — planted-winner promotion, a
controller kill -9 mid-generation with the SAME generation resuming, the
accounting identity, and zero orphaned learners.

This is THE end-to-end smoke for the league subsystem (the fleet_smoke
convention); everything else league-related tests in-process
(``tests/test_league.py``). Learners are the deterministic stub, which
is what keeps the whole script inside the declared fast-tier budget —
asserted here (the tier-1 clock-guard convention, ISSUE 15 satellite).
"""

import os
import subprocess
import sys
import time

from conftest import clean_cpu_env

# The stated fast-tier budget for this smoke. Measured ~5 s on the
# 2-core CI box; 60 s is the convention's ceiling — a regression past it
# means a real-learner leg or an unbounded wait crept in.
FAST_BUDGET_S = 60.0


def test_league_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["LEAGUE_SMOKE_DIR"] = str(tmp_path / "run")
    t0 = time.monotonic()
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "league_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=repo,
    )
    elapsed = time.monotonic() - t0
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "LEAGUE_SMOKE_ASSERTS_OK" in p.stdout, out[-4000:]
    assert "LEAGUE_SMOKE_OK" in p.stdout, out[-4000:]
    # the journal + summary are real on-disk artifacts of the run
    assert os.path.exists(str(tmp_path / "run" / "league" / "league.json"))
    assert elapsed < FAST_BUDGET_S, (
        f"league smoke took {elapsed:.1f}s, past its stated "
        f"{FAST_BUDGET_S:.0f}s fast-tier budget; keep the tier-1 leg on "
        "stub learners (real-learner league runs live in chaos_soak leg 9)"
    )


if __name__ == "__main__":
    sys.exit(0)
