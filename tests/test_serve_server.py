"""Policy server end-to-end over real sockets: happy path, every fault
path a public endpoint must survive, hot reload under live traffic, and
the graceful drain contract."""

import json
import os
import socket
import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.agent import act_deterministic
from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.serve import (
    Overloaded,
    PolicyBundle,
    PolicyClient,
    PolicyServer,
    ShedError,
    export_bundle,
)
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.bundle import actor_template, load_bundle


CFG = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(8, 8))


def _bundle(params=None, path=None):
    return PolicyBundle(
        config=CFG,
        actor_params=params if params is not None else actor_template(CFG),
        action_low=np.full(2, -1.0, np.float32),
        action_high=np.full(2, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "test"},
        path=path,
    )


@pytest.fixture(scope="module")
def server():
    srv = PolicyServer(
        _bundle(), port=0, max_batch=4, max_wait_us=500, queue_limit=16,
        watch_bundle=False,
    )
    srv.start()
    yield srv
    srv.drain()


def test_roundtrip_matches_direct_forward(server):
    rng = np.random.default_rng(3)
    obs = rng.normal(size=4).astype(np.float32)
    with PolicyClient("127.0.0.1", server.port) as c:
        a = c.act(obs)
    ref = np.clip(
        np.asarray(
            act_deterministic(CFG, server.bundle.actor_params, obs[None])[0]
        ),
        -1.0,
        1.0,
    )
    np.testing.assert_allclose(a, ref, rtol=1e-5, atol=1e-6)


def test_pipelined_requests_one_connection(server):
    rng = np.random.default_rng(4)
    obs = rng.normal(size=(16, 4)).astype(np.float32)
    with PolicyClient("127.0.0.1", server.port) as c:
        futs = [c.act_async(o) for o in obs]
        got = np.stack([f.result(30) for f in futs])
    ref = np.clip(
        np.asarray(act_deterministic(CFG, server.bundle.actor_params, obs)),
        -1.0,
        1.0,
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_healthz_schema(server):
    with PolicyClient("127.0.0.1", server.port) as c:
        c.act(np.zeros(4, np.float32))
        h = c.healthz()
    assert h["status"] == "ok"
    assert h["obs_dim"] == 4 and h["action_dim"] == 2
    assert h["replies_ok"] >= 1 and h["requests_total"] >= 1
    assert h["compile_count"] == len(h["buckets"])
    assert "p50_ms" in h and "batch_size_hist" in h and "queue_depth_hist" in h
    assert "shed_total" in h and "params_version" in h


def test_malformed_frame_gets_error_reply_and_close(server):
    before = server.stats.protocol_errors
    s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
    s.sendall(b"GARBAGE-NOT-A-FRAME" + bytes(16))
    msg_type, req_id, payload = protocol.read_frame(s)
    assert msg_type == protocol.ERROR
    assert b"magic" in payload
    assert s.recv(1) == b""  # server closed the connection
    s.close()
    assert server.stats.protocol_errors == before + 1
    # the server is still healthy for the next client
    with PolicyClient("127.0.0.1", server.port) as c:
        assert c.act(np.zeros(4, np.float32)).shape == (2,)


def test_wrong_obs_size_gets_error_reply(server):
    s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
    protocol.write_frame(
        s, protocol.ACT, 5, protocol.encode_act(np.zeros(9, np.float32))
    )
    msg_type, _, payload = protocol.read_frame(s)
    assert msg_type == protocol.ERROR
    assert b"obs_dim" in payload
    s.close()


def test_oversized_request_is_refused(server):
    s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
    # a DECLARED length past the cap must be rejected from the header alone
    # (the server must not try to buffer it)
    s.sendall(
        protocol.HEADER.pack(
            protocol.MAGIC, protocol.PROTOCOL_VERSION, protocol.ACT, 1,
            protocol.MAX_PAYLOAD + 1,
        )
    )
    msg_type, _, payload = protocol.read_frame(s)
    assert msg_type == protocol.ERROR
    assert b"max" in payload
    s.close()


def test_client_disconnect_mid_request_does_not_poison_server(server):
    dropped_before = server.stats.dropped_replies
    s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
    protocol.write_frame(
        s, protocol.ACT, 9, protocol.encode_act(np.zeros(4, np.float32))
    )
    s.close()  # gone before the reply
    deadline = time.time() + 10
    while server.stats.dropped_replies == dropped_before and time.time() < deadline:
        time.sleep(0.01)
    # the reply write may race the close and still succeed; either way the
    # server must keep serving other clients
    with PolicyClient("127.0.0.1", server.port) as c:
        assert c.act(np.zeros(4, np.float32)).shape == (2,)


def test_queue_full_shedding_over_socket():
    """Slow device stub + tiny queue: the client sees explicit OVERLOADED
    (queue_full) replies, never hangs, and admitted requests complete."""
    srv = PolicyServer(
        _bundle(), port=0, max_batch=2, max_wait_us=0, queue_limit=2,
        watch_bundle=False,
    )
    srv.start()
    real = srv.batcher._infer

    def slow(p, o):
        time.sleep(0.3)
        return real(p, o)

    srv.batcher._infer = slow
    try:
        with PolicyClient("127.0.0.1", srv.port) as c:
            obs = np.zeros(4, np.float32)
            futs = [c.act_async(obs) for _ in range(12)]
            outcomes = {"ok": 0, "queue_full": 0}
            for f in futs:
                try:
                    f.result(60)
                    outcomes["ok"] += 1
                except Overloaded as e:
                    assert e.reason == "queue_full"
                    outcomes["queue_full"] += 1
            assert outcomes["queue_full"] >= 1, outcomes
            assert outcomes["ok"] >= 2, outcomes
        assert srv.stats.shed_queue_full >= 1
    finally:
        srv.drain()


def test_hot_reload_during_live_traffic(tmp_path):
    """Params swap mid-traffic: every in-flight and subsequent request gets
    a VALID answer (old or new params, nothing else), none are dropped,
    and the bucket programs never recompile."""
    d = str(tmp_path / "hotbundle")
    params_old = actor_template(CFG)
    export_bundle(d, CFG, params_old)
    # served from the on-disk bundle, watching it; the poll interval is
    # huge on purpose — the test drives reloads via check_reload() so the
    # swap instant is deterministic
    srv = PolicyServer(
        load_bundle(d), port=0, max_batch=4, max_wait_us=500, queue_limit=64,
        watch_bundle=True, poll_interval_s=3600.0,
    )
    srv.start()
    try:
        obs = np.full(4, 0.3, np.float32)
        ref_old = np.clip(
            np.asarray(act_deterministic(CFG, params_old, obs[None])[0]), -1, 1
        )
        params_new = jax.tree_util.tree_map(lambda x: x + 0.5, params_old)
        ref_new = np.clip(
            np.asarray(act_deterministic(CFG, params_new, obs[None])[0]), -1, 1
        )
        compiles = srv.batcher.compile_count
        results = []
        errors = []
        stop = threading.Event()

        def traffic():
            try:
                with PolicyClient("127.0.0.1", srv.port) as c:
                    while not stop.is_set():
                        results.append(c.act(obs, timeout=30))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(0.15)  # traffic flowing on old params
        # re-export over the live bundle dir, mtime changes → reload
        export_bundle(d, CFG, params_new)
        # ensure a visible mtime delta even on coarse filesystem clocks
        os.utime(
            os.path.join(d, "bundle.json"),
            (time.time() + 2, time.time() + 2),
        )
        assert srv.check_reload() is True
        time.sleep(0.15)  # traffic flowing on new params
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert srv.batcher.compile_count == compiles  # zero recompiles
        assert srv.stats.params_reloads == 1
        n_old = n_new = 0
        for a in results:
            if np.allclose(a, ref_old, atol=1e-5):
                n_old += 1
            elif np.allclose(a, ref_new, atol=1e-5):
                n_new += 1
            else:
                raise AssertionError(f"reply matches neither param set: {a}")
        assert n_old >= 1 and n_new >= 1, (n_old, n_new)
    finally:
        srv.drain()


def test_bundle_reload_swaps_obs_norm_and_refuses_config_change(tmp_path):
    """A re-exported bundle's normalizer stats ride the hot swap (new
    params trained under fresher μ/σ must be served with them); a changed
    agent config is refused — the compiled programs are config-shaped."""
    d = str(tmp_path / "b")
    params = actor_template(CFG)
    stats0 = {"count": 4.0, "mean": [0.0] * 4, "m2": [4.0] * 4}
    export_bundle(d, CFG, params, obs_norm_state=stats0)
    srv = PolicyServer(
        load_bundle(d), port=0, max_batch=2, max_wait_us=100,
        watch_bundle=True, poll_interval_s=3600.0,
    )
    srv.start()
    try:
        obs = np.full(4, 2.0, np.float32)
        with PolicyClient("127.0.0.1", srv.port) as c:
            a0 = c.act(obs)
            # re-export with shifted stats: same params, different μ/σ →
            # different served action after reload
            stats1 = {"count": 4.0, "mean": [1.5] * 4, "m2": [1.0] * 4}
            export_bundle(d, CFG, params, obs_norm_state=stats1)
            os.utime(
                os.path.join(d, "bundle.json"),
                (time.time() + 2, time.time() + 2),
            )
            assert srv.check_reload() is True
            a1 = c.act(obs)
            assert not np.allclose(a0, a1)
            mean = np.full(4, 1.5, np.float32)
            std = np.maximum(np.sqrt(np.full(4, 0.25)), 1e-2).astype(np.float32)
            ref = np.clip(
                np.asarray(
                    act_deterministic(
                        CFG, params, np.clip((obs - mean) / std, -5, 5)[None]
                    )[0]
                ),
                -1, 1,
            )
            np.testing.assert_allclose(a1, ref, rtol=1e-5, atol=1e-6)
            # a config change must NOT swap (and must not kill serving)
            other = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(16, 16))
            export_bundle(d, other, actor_template(other), obs_norm_state=stats1)
            os.utime(
                os.path.join(d, "bundle.json"),
                (time.time() + 4, time.time() + 4),
            )
            assert srv.check_reload() is False
            np.testing.assert_allclose(c.act(obs), a1, rtol=1e-5, atol=1e-6)
    finally:
        srv.drain()


def test_watch_run_reloads_best_actor(tmp_path):
    """The --watch-run flow: a new best_eval.json (whose contract says
    best_actor.npz is already on disk) swaps serving params."""
    run = tmp_path / "run"
    ckpt = run / "checkpoints"
    ckpt.mkdir(parents=True)
    params_new = jax.tree_util.tree_map(
        lambda x: x - 0.25, actor_template(CFG)
    )
    leaves = jax.tree_util.tree_leaves(params_new)
    with open(ckpt / "best_actor.npz", "wb") as f:
        np.savez(
            f, **{f"leaf_{i:04d}": np.asarray(l) for i, l in enumerate(leaves)}
        )
    srv = PolicyServer(
        _bundle(), port=0, max_batch=2, max_wait_us=100,
        watch_bundle=False, watch_run=str(run),
    )
    srv.start()
    try:
        assert srv.check_reload() is False  # no best_eval.json yet
        with open(run / "best_eval.json", "w") as f:
            json.dump({"step": 7, "eval_return_mean": 1.0, "env_steps": 10}, f)
        assert srv.check_reload() is True
        obs = np.full(4, -0.2, np.float32)
        ref = np.clip(
            np.asarray(act_deterministic(CFG, params_new, obs[None])[0]), -1, 1
        )
        with PolicyClient("127.0.0.1", srv.port) as c:
            np.testing.assert_allclose(c.act(obs), ref, rtol=1e-5, atol=1e-6)
    finally:
        srv.drain()


def test_drain_sheds_new_answers_admitted():
    srv = PolicyServer(
        _bundle(), port=0, max_batch=2, max_wait_us=0, queue_limit=32,
        watch_bundle=False,
    )
    srv.start()
    real = srv.batcher._infer

    def slow(p, o):
        time.sleep(0.05)
        return real(p, o)

    srv.batcher._infer = slow
    obs = np.zeros(4, np.float32)
    with PolicyClient("127.0.0.1", srv.port) as c:
        futs = [c.act_async(obs) for _ in range(8)]
        time.sleep(0.02)
        drainer = threading.Thread(target=srv.drain, daemon=True)
        drainer.start()
        ok = shed = 0
        for f in futs:
            try:
                f.result(30)
                ok += 1
            except Overloaded as e:
                assert e.reason in ("draining", "queue_full")
                shed += 1
            except Exception:
                shed += 1  # connection torn at the tail of the drain
        drainer.join(timeout=30)
        assert not drainer.is_alive()
        assert ok >= 1  # admitted work was answered, not dropped
    # post-drain: the I/O loop exited and the batcher refuses new work
    assert not srv._loop._thread.is_alive()
    with pytest.raises((ShedError, RuntimeError)):
        srv.batcher.submit(obs)


def test_submit_after_batcher_stop_raises_shed():
    srv = PolicyServer(_bundle(), port=0, max_batch=2, watch_bundle=False)
    srv.start()
    srv.drain()
    with pytest.raises((ShedError, RuntimeError)):
        srv.batcher.submit(np.zeros(4, np.float32))


def test_chaos_sock_reset_drops_one_conn_server_survives():
    """Chaos sock_reset: the targeted connection is force-reset at its Nth
    frame; every other client keeps being served, the reset shows in
    healthz (chaos_injections), and the drain is clean."""
    from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

    inj = ChaosInjector(ChaosPlan.parse("sock_reset@3"))
    srv = PolicyServer(
        _bundle(), port=0, max_batch=4, max_wait_us=500, watch_bundle=False,
        chaos=inj,
    )
    srv.start()
    try:
        obs = np.zeros(4, np.float32)
        with PolicyClient("127.0.0.1", srv.port) as victim:
            assert victim.act(obs).shape == (2,)  # frames 1..2 fine
            assert victim.act(obs).shape == (2,)
            with pytest.raises(Exception):
                victim.act(obs)  # frame 3: injected reset
        assert inj.injections_total == 1
        # the server keeps serving fresh connections at full health
        with PolicyClient("127.0.0.1", srv.port) as c:
            assert c.act(obs).shape == (2,)
            h = c.healthz()
        assert h["status"] == "ok"
        assert h["chaos_injections"] == 1
    finally:
        srv.drain()


def test_healthz_reports_degraded_after_failed_reload(tmp_path):
    """Observability satellite: a failed hot-reload leaves the server
    healthy-but-stale — healthz must say so (status=degraded,
    last_reload=failed: ...) instead of burying it in logs."""
    import os

    run = tmp_path / "run"
    (run / "checkpoints").mkdir(parents=True)
    srv = PolicyServer(
        _bundle(), port=0, max_batch=4, watch_bundle=False,
        watch_run=str(run),
    )
    srv.start()
    try:
        h = srv.healthz()
        assert h["status"] == "ok" and h["last_reload"] is None
        assert h["draining"] is False
        # best_eval.json moves but best_actor.npz is garbage → reload fails
        (run / "best_eval.json").write_text('{"eval_return_mean": 1.0}')
        (run / "checkpoints" / "best_actor.npz").write_bytes(b"not an npz")
        assert srv.check_reload() is False
        h = srv.healthz()
        assert h["status"] == "degraded"
        assert h["last_reload"].startswith("failed")
        # a later successful reload clears the degraded state
        import jax

        from d4pg_tpu.serve.bundle import actor_template

        leaves = jax.tree_util.tree_leaves(actor_template(CFG))
        with open(run / "checkpoints" / "best_actor.npz", "wb") as f:
            np.savez(
                f,
                **{f"leaf_{i:04d}": np.asarray(l) for i, l in enumerate(leaves)},
            )
        st = os.stat(run / "best_eval.json")
        os.utime(run / "best_eval.json", (st.st_atime, st.st_mtime + 5))
        assert srv.check_reload() is True
        h = srv.healthz()
        assert h["status"] == "ok" and h["last_reload"].startswith("ok")
    finally:
        srv.drain()


def test_raw_socket_reply_bytes_pinned(server):
    """Byte-identity at the raw-socket level (ISSUE 20 acceptance):
    handcrafted request bytes in — no client library — and the exact
    reply header layout of the thread-path server out. Any drift in the
    loop's write path (version byte, header order, framing) fails here
    even if the symmetric client library would mask it."""
    with socket.create_connection(("127.0.0.1", server.port), timeout=5) as c:
        # HEALTHZ: v1, header-only request; reply is v1 HEALTHZ_OK
        c.sendall(
            protocol.HEADER.pack(protocol.MAGIC, 1, protocol.HEALTHZ, 77, 0)
        )
        hdr = protocol.recv_exact(c, protocol.HEADER.size)
        magic, ver, typ, rid, ln = protocol.HEADER.unpack(hdr)
        assert (magic, ver, typ, rid) == (
            protocol.MAGIC, 1, protocol.HEALTHZ_OK, 77,
        )
        snap = json.loads(protocol.recv_exact(c, ln))
        assert snap["status"] in ("ok", "degraded")
        assert "netio" in snap  # the loop's counters ride healthz
        # ACT: v1 request; reply header pinned, payload action_dim f32s
        payload = protocol.encode_act(np.zeros(4, np.float32), 0)
        c.sendall(
            protocol.HEADER.pack(
                protocol.MAGIC, 1, protocol.ACT, 78, len(payload)
            )
            + payload
        )
        hdr = protocol.recv_exact(c, protocol.HEADER.size)
        magic, ver, typ, rid, ln = protocol.HEADER.unpack(hdr)
        assert (magic, ver, typ, rid) == (
            protocol.MAGIC, 1, protocol.ACT_OK, 78,
        )
        act = protocol.decode_action(protocol.recv_exact(c, ln))
        assert act.shape == (2,)
    # bad magic: the ENTIRE reply byte stream is pinned — one ERROR
    # frame with read_frame's exact wording, then FIN
    with socket.create_connection(("127.0.0.1", server.port), timeout=5) as c:
        c.sendall(b"XY" + bytes(14))
        expected = protocol.encode_frame(
            protocol.ERROR, 0, b"bad magic b'XY'"
        )
        got = b""
        while len(got) < len(expected):
            chunk = c.recv(4096)
            if not chunk:
                break
            got += chunk
        assert got == expected
        assert c.recv(4096) == b""  # FIN after the notice
