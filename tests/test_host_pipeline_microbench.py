"""Tier-1-safe host data-plane microbench smoke.

Keeps the round-7 host-pipeline perf surface (legacy vs native-block
samplers, per-stage times) exercised every test pass even with the TPU
tunnel down — the committed artifact lives at
``benchmarks/host_pipeline_microbench.json`` (regenerate with
``JAX_PLATFORMS=cpu python benchmarks/host_pipeline_microbench.py``)."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from host_pipeline_microbench import run_microbench  # noqa: E402


def test_microbench_runs_and_records(tmp_path):
    out_path = str(tmp_path / "host_pipeline_microbench.json")
    out = run_microbench(
        out_path, batch=16, rows=512, steps=4, hidden=16, ks=(2,), repeats=1
    )
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "host_pipeline_microbench"
    for name in ("legacy_auto_k2", "block_auto_k2", "legacy_numpy_k2", "block_numpy_k2"):
        v = out[name]
        assert v["host_ms_per_dispatch"] > 0 and np.isfinite(v["host_ms_per_dispatch"])
        for stage in ("sample", "h2d_stage", "train_dispatch", "priority_writeback"):
            assert v["stage_ms_per_dispatch"][stage] >= 0.0
        assert len(v["host_ms_repeats"]) == 1
    # the numpy rows really ran the numpy trees; auto resolved to SOME backend
    assert out["legacy_numpy_k2"]["tree_backend"] == "numpy"
    assert out["block_auto_k2"]["tree_backend"] in ("native", "numpy")
    assert "host_ms_ratio_k2" in out


def test_committed_artifact_is_current_schema():
    """The committed artifact must stay parseable and carry the per-stage
    before/after keys (schema drift would blind the host-perf guard)."""
    path = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks",
        "host_pipeline_microbench.json",
    )
    with open(path) as f:
        art = json.load(f)
    assert art["metric"] == "host_pipeline_microbench"
    for k in (1, 8):
        for variant in (f"legacy_auto_k{k}", f"block_auto_k{k}"):
            v = art[variant]
            assert v["host_ms_per_dispatch"] > 0
            assert set(v["stage_ms_per_dispatch"]) >= {
                "sample", "h2d_stage", "train_dispatch", "priority_writeback"
            }
        assert f"host_ms_ratio_k{k}" in art
