"""Graceful preemption: the SIGTERM/SIGINT → checkpoint → exit-75 path.

The fast tests drive :meth:`Trainer.request_preemption` directly (the
signal handler's only action) so tier-1 covers the checkpoint-and-stop
contract without process games; the slow test delivers a real SIGTERM to a
live ``train.py`` subprocess and asserts the full contract — exit 75,
trainer meta, replay snapshot — i.e. what a TPU-VM preemption notice sees.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from d4pg_tpu.runtime.trainer import Trainer
from train import build_parser, config_from_args, install_preemption_handlers


def _tiny_args(tmp, extra=()):
    return build_parser().parse_args(
        [
            "--env", "pendulum",
            "--total-steps", "6",
            "--warmup", "130",
            "--eval-interval", "6",
            "--checkpoint-interval", "6",
            "--num-envs", "2",
            "--bsize", "16",
            "--log-dir", str(tmp),
            *extra,
        ]
    )


def test_preempt_before_train_checkpoints_and_stops(tmp_path):
    t = Trainer(config_from_args(_tiny_args(tmp_path / "a")))
    t.request_preemption()
    out = t.train()
    t.close()
    assert t.preempted
    assert out == {}  # no grad steps ran, no eval row
    # the preemption checkpoint landed: meta + an Orbax step
    assert os.path.exists(tmp_path / "a" / "checkpoints" / "trainer_meta.json")
    assert t.ckpt.latest_step() is not None


def test_preempt_mid_train_saves_and_resumes(tmp_path):
    cfg = config_from_args(
        _tiny_args(tmp_path / "b", ("--total-steps", "100000"))
    )
    t = Trainer(cfg)
    # arm the preemption shortly after the loop starts making progress
    def arm():
        while t.grad_steps < 2:
            time.sleep(0.01)
        t.request_preemption()

    th = threading.Thread(target=arm, daemon=True)
    th.start()
    t.train()
    th.join(timeout=30)
    saved_step = t.ckpt.latest_step()
    t.close()
    assert t.preempted
    assert saved_step is not None and saved_step >= 2
    # a --resume leg picks up from the preemption checkpoint
    t2 = Trainer(
        config_from_args(
            _tiny_args(
                tmp_path / "b",
                ("--total-steps", str(saved_step + 2), "--resume"),
            )
        )
    )
    assert t2.grad_steps == saved_step
    t2.close()


def test_install_preemption_handlers_wiring():
    """The installed handler calls the stop callback on the FIRST signal
    and restores the default disposition so a second one hard-kills."""
    fired = []
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    try:
        install_preemption_handlers(lambda: fired.append(True))
        signal.raise_signal(signal.SIGTERM)
        assert fired == [True]
        assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL
        # SIGINT handler is independent and still armed
        assert signal.getsignal(signal.SIGINT) is not signal.SIG_DFL
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)


@pytest.mark.slow
def test_sigterm_on_live_training_run_exits_75(tmp_path):
    """Full contract over a real process: SIGTERM mid-run → checkpoint +
    replay snapshot + exit code 75 (EX_TEMPFAIL, the --resume handshake)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
        and "AXON" not in k
        and "TPU" not in k
    }
    env["JAX_PLATFORMS"] = "cpu"
    run = str(tmp_path / "run")
    proc = subprocess.Popen(
        [
            sys.executable, "train.py",
            "--env", "Pendulum-v1", "--hidden-sizes", "16,16",
            "--total-steps", "100000", "--warmup", "16",
            "--bsize", "8", "--rmsize", "512",
            "--eval-interval", "100000", "--checkpoint-interval", "100000",
            "--num-envs", "1", "--snapshot-replay", "--log-dir", run,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    lines = []

    def pump():
        for line in proc.stdout:
            lines.append(line)

    th = threading.Thread(target=pump, daemon=True)
    th.start()
    deadline = time.time() + 300
    while time.time() < deadline and not any("config:" in l for l in lines):
        time.sleep(0.5)
    time.sleep(20)  # past warmup, into grad steps
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=240)
    th.join(timeout=10)
    out = "".join(lines)
    assert rc == 75, out[-3000:]
    assert "[preempt]" in out
    assert os.path.exists(os.path.join(run, "checkpoints", "trainer_meta.json"))
    assert os.path.exists(os.path.join(run, "checkpoints", "replay.npz"))
