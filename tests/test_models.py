"""Shape/init/semantics tests for the Flax modules."""

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.models import Actor, Critic, DistConfig, PixelEncoder
from d4pg_tpu.models.critic import mixture_gaussian_mean


def test_actor_shapes_and_range():
    actor = Actor(action_dim=6)
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((1, 17)))
    out = actor.apply(params, jnp.ones((32, 17)) * 100.0)
    assert out.shape == (32, 6)
    assert np.all(np.abs(np.asarray(out)) <= 1.0)


def test_actor_hidden_layers_have_relu_between():
    # Two distinct hidden mats must not collapse: output of a 2-hidden-layer
    # actor on x and -x should differ in magnitude (ReLU nonlinearity), unlike
    # a purely linear stack where f(-x)+f(x)-2f(0) == 0.
    actor = Actor(action_dim=1, hidden_sizes=(16, 16), final_init_scale=1.0)
    params = actor.init(jax.random.PRNGKey(1), jnp.zeros((1, 4)))

    def pre_tanh(x):
        return np.arctanh(np.clip(np.asarray(actor.apply(params, x)), -0.999999, 0.999999))

    x = jnp.ones((1, 4)) * 0.5
    resid = pre_tanh(x) + pre_tanh(-x) - 2 * pre_tanh(jnp.zeros((1, 4)))
    assert np.abs(resid).max() > 1e-6


def test_critic_categorical_head():
    dist = DistConfig(kind="categorical", num_atoms=51)
    critic = Critic(dist=dist)
    params = critic.init(jax.random.PRNGKey(0), jnp.zeros((1, 17)), jnp.zeros((1, 6)))
    logits = critic.apply(params, jnp.ones((8, 17)), jnp.ones((8, 6)))
    assert logits.shape == (8, 51)
    probs = jax.nn.softmax(logits)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, atol=1e-5)


def test_critic_scalar_head():
    critic = Critic(dist=DistConfig(kind="scalar"))
    params = critic.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)), jnp.zeros((1, 1)))
    q = critic.apply(params, jnp.ones((4, 3)), jnp.ones((4, 1)))
    assert q.shape == (4, 1)


def test_critic_mixture_head():
    dist = DistConfig(kind="mixture_gaussian", num_mixtures=5)
    critic = Critic(dist=dist)
    params = critic.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)), jnp.zeros((1, 1)))
    head = critic.apply(params, jnp.ones((4, 3)), jnp.ones((4, 1)))
    assert head.shape == (4, 15)
    mean = mixture_gaussian_mean(head, 5)
    assert mean.shape == (4,)
    assert np.all(np.isfinite(np.asarray(mean)))


def test_critic_depends_on_action():
    critic = Critic(dist=DistConfig(kind="scalar"))
    params = critic.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)), jnp.zeros((1, 2)))
    q1 = critic.apply(params, jnp.ones((1, 3)), jnp.zeros((1, 2)))
    q2 = critic.apply(params, jnp.ones((1, 3)), jnp.ones((1, 2)))
    assert float(jnp.abs(q1 - q2).sum()) > 1e-6


def test_fanin_init_bounds():
    actor = Actor(action_dim=2)
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((1, 100)))
    k = np.asarray(params["params"]["hidden_0"]["kernel"])
    bound = 1.0 / np.sqrt(100)
    assert np.abs(k).max() <= bound + 1e-7
    out_k = np.asarray(params["params"]["out"]["kernel"])
    assert np.abs(out_k).max() <= 3e-3 + 1e-7


def test_pixel_encoder():
    enc = PixelEncoder()
    params = enc.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    z = enc.apply(params, jnp.ones((2, 64, 64, 3)))
    assert z.shape == (2, 50)
    assert np.all(np.abs(np.asarray(z)) <= 1.0)
    # byte-range inputs are declared via a fixed input_scale, not guessed
    # per batch; same pixels under either convention embed identically
    enc255 = PixelEncoder(input_scale=255.0)
    z255 = enc255.apply(params, jnp.ones((2, 64, 64, 3)) * 255.0)
    np.testing.assert_allclose(np.asarray(z255), np.asarray(z), atol=1e-6)
