"""Tests for the fused train step: shapes, learning signal, all critic heads."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.agent import (
    D4PGConfig,
    act,
    act_deterministic,
    create_train_state,
    jit_train_step,
    support_of,
)
from d4pg_tpu.models.critic import DistConfig


def _batch(rng, B=32, obs_dim=3, act_dim=1):
    return {
        "obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, size=(B, act_dim)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, size=B), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "discount": jnp.full((B,), 0.99, jnp.float32),
        "weights": jnp.ones((B,), jnp.float32),
    }


@pytest.mark.parametrize("kind", ["categorical", "scalar", "mixture_gaussian"])
def test_train_step_runs_and_updates(kind):
    config = D4PGConfig(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(32, 32),
        dist=DistConfig(kind=kind, num_atoms=21, v_min=-5, v_max=5, num_mixtures=3),
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    step = jit_train_step(config, donate=False)
    rng = np.random.default_rng(0)
    state2, metrics, priorities = step(state, _batch(rng))
    assert int(state2.step) == 1
    assert priorities.shape == (32,)
    assert np.all(np.asarray(priorities) >= 0) or kind == "mixture_gaussian"
    for v in metrics.values():
        assert np.isfinite(float(v))
    # Saturation monitor: q_support_frac is (q_mean - v_min)/(v_max - v_min)
    # — the runtime tripwire for a clipped value distribution (the Humanoid
    # v1500 post-mortem, VERDICT round-4 weak #1). Categorical head only:
    # scalar/MoG heads are unbounded, so the ratio would be alarm noise.
    if kind == "categorical":
        expect = (float(metrics["q_mean"]) - config.dist.v_min) / (
            config.dist.v_max - config.dist.v_min
        )
        assert float(metrics["q_support_frac"]) == pytest.approx(expect, rel=1e-5)
    else:
        assert "q_support_frac" not in metrics
    # params actually moved
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()), state.critic_params, state2.critic_params
    )
    assert max(jax.tree_util.tree_leaves(moved)) > 0


@pytest.mark.parametrize(
    "opts",
    [
        {},
        {"twin_critic": True},
        {"compute_dtype": "bfloat16"},
        {"priority_kind": "overlap"},
    ],
)
def test_pallas_fused_train_step_matches_xla(opts):
    """Whole-train-step oracle equivalence for projection_backend=
    pallas_fused (interpret mode on CPU): same batch, same init → same
    loss, priorities and updated params as the XLA path, across twin
    critics (vmapped kernel), the bf16 hot path (f32 masters) and both
    priority kinds."""
    base = D4PGConfig(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(32, 32),
        dist=DistConfig(kind="categorical", num_atoms=51, v_min=-10, v_max=10),
    )
    cfg_xla = dataclasses.replace(base, projection_backend="xla", **opts)
    cfg_fused = dataclasses.replace(base, projection_backend="pallas_fused", **opts)
    state = create_train_state(cfg_xla, jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    batch = _batch(rng)
    s1, m1, p1 = jit_train_step(cfg_xla, donate=False)(state, batch)
    s2, m2, p2 = jit_train_step(cfg_fused, donate=False)(state, batch)
    assert float(m1["critic_loss"]) == pytest.approx(
        float(m2["critic_loss"]), abs=1e-5
    )
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.critic_params),
        jax.tree_util.tree_leaves(s2.critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_bf16_masters_stay_f32():
    """bf16 hot-path policy: master weights, Adam moments and Polyak
    targets remain f32 after a bf16 train step (the one-shot target cast
    is internal to the step)."""
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(16, 16),
        compute_dtype="bfloat16",
        dist=DistConfig(kind="categorical", num_atoms=21, v_min=-5, v_max=5),
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    state2, _, _ = jit_train_step(config, donate=False)(state, _batch(rng))
    for tree in (
        state2.actor_params,
        state2.critic_params,
        state2.target_actor_params,
        state2.target_critic_params,
    ):
        for leaf in jax.tree_util.tree_leaves(tree):
            assert leaf.dtype == jnp.float32


def test_exploration_mixture():
    """HER-DDPG ε-uniform mixture (round 5): identity at eps=0, full
    replacement at eps=1, whole-vector replacement (never per-dim)."""
    from d4pg_tpu.agent.d4pg import exploration_mixture

    base = D4PGConfig(obs_dim=3, action_dim=4)
    a = jnp.full((16, 4), 0.5)
    k = jax.random.PRNGKey(0)
    assert exploration_mixture(base, k, a) is a  # eps=0: no-op, same object
    cfg1 = dataclasses.replace(base, random_eps=1.0)
    out = np.asarray(exploration_mixture(cfg1, k, a))
    assert not np.any(out == 0.5) and np.all(np.abs(out) <= 1.0)
    cfg03 = dataclasses.replace(base, random_eps=0.3)
    out = np.asarray(exploration_mixture(cfg03, jax.random.PRNGKey(1), a))
    replaced = ~np.all(out == 0.5, axis=-1)
    kept = np.all(out == 0.5, axis=-1)
    # whole vectors: each row is either fully original or fully resampled
    # (uniform draws almost surely never hit exactly 0.5)
    assert np.all(replaced | kept) and replaced.any() and kept.any()


def test_action_l2_regularizes_and_keeps_q_mean_honest():
    """action_l2 must change the actor update AND leave the q_mean metric
    reporting the unpenalized E[Q] (the support-saturation monitor feeds
    off it)."""
    base = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(32, 32))
    reg = dataclasses.replace(base, action_l2=1.0)
    rng = np.random.default_rng(0)
    batch = _batch(rng)
    s0 = create_train_state(base, jax.random.PRNGKey(0))
    s0r = create_train_state(reg, jax.random.PRNGKey(0))
    st_b, m_b, _ = jit_train_step(base, donate=False)(s0, batch)
    st_r, m_r, _ = jit_train_step(reg, donate=False)(s0r, batch)
    # same init, same batch: penalty shifts the loss by ~mean(a^2) but the
    # reported q_mean (aux) must match the unregularized one exactly
    assert float(m_r["actor_loss"]) != pytest.approx(float(m_b["actor_loss"]))
    assert float(m_r["q_mean"]) == pytest.approx(float(m_b["q_mean"]), rel=1e-5)
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()), st_b.actor_params, st_r.actor_params
    )
    assert max(jax.tree_util.tree_leaves(moved)) > 0  # different updates


@pytest.mark.parametrize("kind", ["categorical", "scalar", "mixture_gaussian"])
def test_twin_critic_train_step(kind):
    """Twin critics (clipped double-Q): stacked [2] critic pytree trains,
    both critics move, priorities stay per-sample."""
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(32, 32), twin_critic=True,
        dist=DistConfig(kind=kind, num_atoms=21, v_min=-5, v_max=5, num_mixtures=3),
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    # stacked leading axis, and the two inits are independent
    leaf = jax.tree_util.tree_leaves(state.critic_params)[0]
    assert leaf.shape[0] == 2
    kernels = [
        l for l in jax.tree_util.tree_leaves(state.critic_params) if l.ndim == 3
    ]
    assert any(float(jnp.abs(k[0] - k[1]).max()) > 0 for k in kernels)
    step = jit_train_step(config, donate=False)
    rng = np.random.default_rng(0)
    state2, metrics, priorities = step(state, _batch(rng))
    assert priorities.shape == (32,)
    for v in metrics.values():
        assert np.isfinite(float(v))
    # BOTH critics moved (sum of per-critic losses backprops to each slice)
    for i in (0, 1):
        moved = jax.tree_util.tree_map(
            lambda a, b: float(jnp.abs(a[i] - b[i]).max()),
            state.critic_params, state2.critic_params,
        )
        assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_twin_critic_target_is_min_of_means():
    """The Bellman backup must use the target critic with the SMALLER
    expected value per sample (TD3's clipped double-Q, distributional)."""
    from d4pg_tpu.agent.d4pg import _critic_value, build_networks, support_of

    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(16, 16), twin_critic=True,
        dist=DistConfig(kind="categorical", num_atoms=21, v_min=-5, v_max=5),
    )
    state = create_train_state(config, jax.random.PRNGKey(1))
    _, critic = build_networks(config)
    support = support_of(config)
    rng = np.random.default_rng(1)
    batch = _batch(rng)
    from d4pg_tpu.agent.d4pg import act_deterministic

    next_a = act_deterministic(config, state.target_actor_params, batch["next_obs"])
    heads = jax.vmap(
        lambda p: critic.apply(p, batch["next_obs"], next_a)
    )(state.target_critic_params)
    vals = jax.vmap(lambda h: _critic_value(config, support, h))(heads)
    picked = jnp.where((vals[0] <= vals[1])[..., None], heads[0], heads[1])
    picked_vals = _critic_value(config, support, picked)
    # fresh-init expected values sit near 0, so rtol alone is meaningless;
    # atol covers softmax reassociation noise on the gathered head
    np.testing.assert_allclose(
        np.asarray(picked_vals), np.minimum(*np.asarray(vals)),
        rtol=1e-5, atol=1e-6,
    )


def test_critic_loss_decreases_on_fixed_batch():
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(64, 64), tau=0.005)
    state = create_train_state(config, jax.random.PRNGKey(1))
    step = jit_train_step(config, donate=False)
    rng = np.random.default_rng(1)
    batch = _batch(rng, B=64)
    losses = []
    for _ in range(150):
        state, metrics, _ = step(state, batch)
        losses.append(float(metrics["critic_loss"]))
    assert losses[-1] < losses[0]


def test_target_params_lag_online():
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16), tau=0.01)
    state = create_train_state(config, jax.random.PRNGKey(2))
    step = jit_train_step(config, donate=False)
    batch = _batch(np.random.default_rng(2))
    state2, _, _ = step(state, batch)
    # target moved tau of the way toward new online params
    on0 = state.critic_params["params"]["out"]["kernel"]
    on1 = state2.critic_params["params"]["out"]["kernel"]
    tg1 = state2.target_critic_params["params"]["out"]["kernel"]
    np.testing.assert_allclose(
        np.asarray(tg1), np.asarray(0.99 * on0 + 0.01 * on1), rtol=1e-5, atol=1e-7
    )


def test_priorities_overlap_mode_matches_reference_surrogate():
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16), priority_kind="overlap")
    state = create_train_state(config, jax.random.PRNGKey(3))
    step = jit_train_step(config, donate=False)
    _, _, pri = step(state, _batch(np.random.default_rng(3)))
    # overlap surrogate is a probability-mass dot product: in [0, 1]
    assert np.all(np.asarray(pri) >= 0) and np.all(np.asarray(pri) <= 1.0)


def test_act_explores_and_eval_is_deterministic():
    config = D4PGConfig(obs_dim=3, action_dim=2, hidden_sizes=(16, 16))
    state = create_train_state(config, jax.random.PRNGKey(4))
    obs = jnp.zeros((5, 3))
    a1 = act(config, state.actor_params, obs, jax.random.PRNGKey(0))
    a2 = act(config, state.actor_params, obs, jax.random.PRNGKey(1))
    assert np.abs(np.asarray(a1 - a2)).max() > 0
    assert np.all(np.abs(np.asarray(a1)) <= 1.0)
    d1 = act_deterministic(config, state.actor_params, obs)
    d2 = act_deterministic(config, state.actor_params, obs)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_bfloat16_compute_path():
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(32, 32), compute_dtype="bfloat16"
    )
    state = create_train_state(config, jax.random.PRNGKey(5))
    step = jit_train_step(config, donate=False)
    state2, metrics, _ = step(state, _batch(np.random.default_rng(5)))
    assert np.isfinite(float(metrics["critic_loss"]))
    # params remain float32 master copies
    k = state2.critic_params["params"]["out"]["kernel"]
    assert k.dtype == jnp.float32
