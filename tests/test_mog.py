"""Mixture-of-Gaussians distributional Bellman backup (ops/mog.py).

The reference declares this head and leaves it empty (ddpg.py:48-50,
224-226); these tests pin the real operator: affine component transform,
quadrature-CE correctness against closed forms, the terminal-collapse
limit, and (slow) an agent actually learning Pendulum with the head.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.ops import mog_bellman_targets, mog_cross_entropy, mog_log_prob


def _head(log_w, means, stds):
    """Pack (weights, means, stds) rows into the raw 3M head layout
    (logits | means | log_stds) that mixture_gaussian_params splits."""
    log_w = np.asarray(log_w, np.float32)
    means = np.asarray(means, np.float32)
    stds = np.asarray(stds, np.float32)
    return jnp.asarray(
        np.concatenate([log_w, means, np.log(stds)], axis=-1), jnp.float32
    )


def test_bellman_targets_affine_transform():
    """T Z' nodes are r + d·(target component nodes): exact affine map of
    each component, weights = mixture weights × quadrature weights."""
    head = _head([[0.0, 0.0]], [[1.0, -2.0]], [[0.5, 1.0]])  # M=2, equal w
    r = jnp.asarray([3.0])
    d = jnp.asarray([0.9])
    y, w = mog_bellman_targets(head, r, d, num_mixtures=2, quadrature_points=4)
    assert y.shape == (1, 2, 4) and w.shape == (1, 2, 4)
    np.testing.assert_allclose(float(jnp.sum(w)), 1.0, atol=1e-6)
    # E[T Z'] from the quadrature == r + d·E[Z'] analytically
    np.testing.assert_allclose(
        float(jnp.sum(y * w)), 3.0 + 0.9 * (0.5 * 1.0 + 0.5 * -2.0), atol=1e-5
    )
    # node spread of component j scales with d·s_j
    spread0 = float(y[0, 0].max() - y[0, 0].min())
    spread1 = float(y[0, 1].max() - y[0, 1].min())
    np.testing.assert_allclose(spread1 / spread0, 2.0, rtol=1e-5)


def test_terminal_collapses_to_reward_point_mass():
    """d=0: every node sits at r (std floor only keeps quadrature finite)."""
    head = _head([[0.3, -0.7]], [[5.0, -5.0]], [[2.0, 0.1]])
    y, w = mog_bellman_targets(
        head, jnp.asarray([-1.5]), jnp.asarray([0.0]), num_mixtures=2
    )
    np.testing.assert_allclose(np.asarray(y), -1.5, atol=0.01)
    np.testing.assert_allclose(float(jnp.sum(w)), 1.0, atol=1e-6)


def test_log_prob_matches_scipy_style_closed_form():
    """Single-component mixture log-density == Gaussian log-pdf."""
    head = _head([[0.0]], [[1.0]], [[0.7]])
    ys = jnp.asarray([[0.0, 1.0, 2.5]])
    got = mog_log_prob(head, ys, num_mixtures=1)
    want = (
        -0.5 * ((np.asarray(ys) - 1.0) / 0.7) ** 2
        - np.log(0.7)
        - 0.5 * np.log(2 * np.pi)
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_cross_entropy_of_gaussian_with_itself_is_entropy():
    """H(N, N) = differential entropy ½log(2πe σ²) — the quadrature must
    recover it (exact for this integrand up to quadrature error)."""
    sigma = 0.8
    head = _head([[0.0]], [[2.0]], [[sigma]])
    # target == online, identity Bellman transform (r=0, d=1)
    y, w = mog_bellman_targets(
        head, jnp.zeros(1), jnp.ones(1), num_mixtures=1, quadrature_points=16
    )
    ce = float(mog_cross_entropy(head, y, w, num_mixtures=1)[0])
    want = 0.5 * np.log(2 * np.pi * np.e * sigma**2)
    np.testing.assert_allclose(ce, want, rtol=1e-3)


def test_cross_entropy_minimized_at_matching_distribution():
    """H(T Z', Z) over Z is minimized when Z == T Z' (Gibbs): any shifted,
    widened or narrowed online head scores strictly worse."""
    r, d = jnp.asarray([1.0]), jnp.asarray([0.5])
    target = _head([[0.2, -0.2]], [[0.0, 4.0]], [[0.5, 1.0]])
    y, w = mog_bellman_targets(target, r, d, num_mixtures=2, quadrature_points=16)
    # the matching online head IS the transformed target
    match = _head(
        [[0.2, -0.2]],
        [[1.0 + 0.5 * 0.0, 1.0 + 0.5 * 4.0]],
        [[0.5 * 0.5, 0.5 * 1.0]],
    )
    ce_match = float(mog_cross_entropy(match, y, w, num_mixtures=2)[0])
    for head in (
        _head([[0.2, -0.2]], [[1.5, 3.5]], [[0.25, 0.5]]),   # shifted means
        _head([[0.2, -0.2]], [[1.0, 3.0]], [[1.0, 2.0]]),    # widened
        _head([[0.2, -0.2]], [[1.0, 3.0]], [[0.05, 0.1]]),   # narrowed
        _head([[3.0, -3.0]], [[1.0, 3.0]], [[0.25, 0.5]]),   # wrong weights
    ):
        ce_other = float(mog_cross_entropy(head, y, w, num_mixtures=2)[0])
        assert ce_other > ce_match + 1e-3, (ce_other, ce_match)


def test_mog_critic_fits_known_bimodal_distribution():
    """Gradient descent on the quadrature CE recovers a KNOWN target: start
    from a generic head, fit T Z' of a fixed bimodal mixture; the fitted
    mixture's mean and spread must match the transformed target's."""
    import optax

    r, d = jnp.asarray([2.0]), jnp.asarray([0.8])
    target = _head([[0.0, 0.0]], [[-3.0, 3.0]], [[0.5, 0.5]])
    y, w = mog_bellman_targets(target, r, d, num_mixtures=2, quadrature_points=16)
    # transformed target: means 2±2.4, stds 0.4 → E=2.0, Var=0.4²+2.4²
    head0 = jnp.asarray(np.concatenate(
        [[0.1, -0.1], [0.0, 1.0], np.log([1.5, 1.5])]
    ).astype(np.float32))[None]
    opt = optax.adam(5e-2)
    opt_state = opt.init(head0)

    @jax.jit
    def step(head, opt_state):
        loss, g = jax.value_and_grad(
            lambda h: jnp.mean(mog_cross_entropy(h, y, w, 2))
        )(head)
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(head, upd), opt_state, loss

    head = head0
    for _ in range(800):
        head, opt_state, loss = step(head, opt_state)
    from d4pg_tpu.models.critic import mixture_gaussian_params

    log_wf, mf, sf = mixture_gaussian_params(head, 2)
    wf = np.exp(np.asarray(log_wf))[0]
    mf, sf = np.asarray(mf)[0], np.asarray(sf)[0]
    mean = float((wf * mf).sum())
    var = float((wf * (sf**2 + mf**2)).sum() - mean**2)
    np.testing.assert_allclose(mean, 2.0, atol=0.05)
    np.testing.assert_allclose(var, 0.4**2 + 2.4**2, rtol=0.05)
    # it actually split into two modes near 2±2.4
    np.testing.assert_allclose(sorted(mf), [2 - 2.4, 2 + 2.4], atol=0.15)


def test_std_floor_keeps_terminal_quadrature_finite():
    """The std floor is the invariant the terminal collapse leans on: at
    d=0 every projected component std is exactly the floor (not 0 — the
    log-density would be -inf and the CE NaN), and the loss through it
    stays finite."""
    from d4pg_tpu.ops.mog import _STD_FLOOR

    head = _head([[0.0, 0.0]], [[3.0, -3.0]], [[2.0, 0.5]])
    y, w = mog_bellman_targets(
        head, jnp.asarray([1.0]), jnp.asarray([0.0]), num_mixtures=2,
        quadrature_points=8,
    )
    # node spread of each component == sqrt(2)·floor·(x_max - x_min): the
    # floor, not zero, sets the terminal width
    import numpy.polynomial.hermite as H

    nodes, _ = H.hermgauss(8)
    want_spread = np.sqrt(2.0) * _STD_FLOOR * (nodes.max() - nodes.min())
    got_spread = np.asarray(y.max(axis=-1) - y.min(axis=-1))
    np.testing.assert_allclose(got_spread, want_spread, rtol=1e-4)
    ce = mog_cross_entropy(head, y, w, num_mixtures=2)
    assert np.isfinite(np.asarray(ce)).all()


def test_quadrature_matches_monte_carlo_at_high_q():
    """Gauss-Hermite CE vs a large Monte-Carlo estimate of
    -E_{y~TZ'}[log p_online(y)]: agreement within MC error at high Q —
    the quadrature is an integral estimator, not a heuristic."""
    rng = np.random.default_rng(0)
    target = _head([[0.5, -0.5]], [[-2.0, 4.0]], [[0.7, 1.3]])
    online = _head([[0.1, -0.1]], [[0.0, 3.0]], [[1.0, 1.5]])
    r, d = jnp.asarray([1.5]), jnp.asarray([0.8])
    y, w = mog_bellman_targets(target, r, d, num_mixtures=2,
                               quadrature_points=32)
    ce_quad = float(mog_cross_entropy(online, y, w, num_mixtures=2)[0])
    # MC: sample the transformed target mixture directly
    from d4pg_tpu.models.critic import mixture_gaussian_params

    log_wt, m_t, s_t = mixture_gaussian_params(jnp.asarray(target), 2)
    wt = np.exp(np.asarray(log_wt))[0]
    m_proj = 1.5 + 0.8 * np.asarray(m_t)[0]
    s_proj = np.maximum(0.8 * np.asarray(s_t)[0], 1e-3)
    n = 200_000
    comp = rng.choice(2, size=n, p=wt / wt.sum())
    ys = rng.normal(m_proj[comp], s_proj[comp]).astype(np.float32)
    log_p = mog_log_prob(online, jnp.asarray(ys)[None, :], num_mixtures=2)
    ce_mc = float(-jnp.mean(log_p))
    se = float(jnp.std(-log_p)) / np.sqrt(n)
    assert abs(ce_quad - ce_mc) < 5 * se + 5e-3, (ce_quad, ce_mc, se)


def test_grad_flows_through_all_head_components():
    """The CE loss the train step minimizes must carry gradient to EVERY
    online head component — logits, means, and log-stds; a dead slice
    here would silently freeze a third of the head (the exact failure
    mode of a stop_gradient landing on the wrong side)."""
    target = _head([[0.0, 0.0]], [[-1.0, 2.0]], [[0.5, 1.0]])
    online = _head([[0.2, -0.2]], [[0.5, 1.5]], [[0.8, 1.2]])
    y, w = mog_bellman_targets(
        target, jnp.asarray([0.3]), jnp.asarray([0.9]), num_mixtures=2
    )
    g = jax.grad(
        lambda h: jnp.mean(mog_cross_entropy(h, y, w, num_mixtures=2))
    )(online)
    g = np.asarray(g)[0]
    assert np.isfinite(g).all()
    M = 2
    for sl, name in ((slice(0, M), "logits"), (slice(M, 2 * M), "means"),
                     (slice(2 * M, 3 * M), "log_stds")):
        assert np.abs(g[sl]).max() > 0, f"dead gradient slice: {name}"
    # and the TARGET side carries none (stop_gradient contract)
    gt = jax.grad(
        lambda t: jnp.mean(
            mog_cross_entropy(
                online, *mog_bellman_targets(
                    t, jnp.asarray([0.3]), jnp.asarray([0.9]), 2
                ), num_mixtures=2,
            )
        )
    )(target)
    assert float(jnp.abs(gt).max()) == 0.0


@pytest.mark.slow
def test_on_device_mog_head_learns_pendulum_signal():
    """The head is not just well-posed — an agent LEARNS with it (VERDICT
    round-1 weak #1: 'no test shows an agent learning with it')."""
    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.envs import Pendulum
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.runtime import evaluate
    from d4pg_tpu.runtime.on_device import make_on_device_trainer

    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(64, 64),
        dist=DistConfig(kind="mixture_gaussian", num_mixtures=5, v_min=-300.0, v_max=0.0),
        n_step=3, tau=0.005, lr_actor=5e-4, lr_critic=5e-4,
    )
    env = Pendulum()
    init_fn, _warmup, iterate_fn = make_on_device_trainer(
        config, env, num_envs=16, segment_len=32,
        replay_capacity=65_536, batch_size=128, train_steps_per_iter=64,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    carry = init_fn(state, jax.random.PRNGKey(1))
    for _ in range(150):
        carry, metrics = iterate_fn(carry, 1.0)
    assert np.isfinite(float(metrics["critic_loss"]))
    trained = evaluate(config, env, carry[0].actor_params, jax.random.PRNGKey(7), 10)
    base = evaluate(
        config, env,
        create_train_state(config, jax.random.PRNGKey(123)).actor_params,
        jax.random.PRNGKey(7), 10,
    )
    assert trained["eval_return_mean"] > base["eval_return_mean"] + 250
