"""Event-loop I/O core (`d4pg_tpu/netio`): framing byte-parity against
the blocking-path codec, connection-attack eviction (slowloris drip,
zero-window staller), EMFILE shed-not-die, the drain contract, and the
chaos attacker plumbing — all over real sockets against a live
FrameLoop. No JAX anywhere: the loop moves bytes, never tensors."""

import errno
import io
import random
import socket
import threading
import time

import pytest

from d4pg_tpu import chaos as chaos_mod
from d4pg_tpu.netio import FrameLoop
from d4pg_tpu.netio import attack as netio_attack
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import (
    HEADER,
    MAGIC,
    MAX_PAYLOAD,
    PROTOCOL_VERSION,
    FrameAssembler,
    ProtocolError,
    encode_frame,
    read_frame,
    write_frame,
)


def _deadline_wait(pred, timeout_s=8.0, tick=0.02):
    """Poll ``pred`` until true or timeout; returns its final value."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(tick)
    return pred()


class _EchoLoop:
    """A FrameLoop that echoes every frame back — the minimal on-the-wire
    peer for framing/eviction tests."""

    def __init__(self, on_open=None, **loop_kw):
        self.loop = FrameLoop(name="test-io", **loop_kw)
        sock = socket.create_server(("127.0.0.1", 0))
        self.port = sock.getsockname()[1]
        self.loop.serve(
            sock,
            on_frame=lambda conn, t, r, p: conn.send(t, r, p),
            on_open=on_open,
        )
        self.loop.start()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.loop.close(flush_timeout_s=2.0)

    def connect(self, timeout=5.0):
        c = socket.create_connection(("127.0.0.1", self.port), timeout=timeout)
        c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c


# --------------------------------------------------------------- byte parity
def _frames_via_read_frame(blob: bytes):
    """Decode with the BLOCKING path (read_frame over a buffered file) —
    the reference the assembler must match byte-for-byte."""
    stream = io.BytesIO(blob)
    out = []
    while True:
        f = read_frame(stream)
        if f is None:
            return out, None
        out.append(f)


def _frames_via_assembler(blob: bytes, rng: random.Random):
    """Decode with the loop path: feed random-sized chunks, drain, then
    report EOF exactly as FrameLoop._on_readable does."""
    asm = FrameAssembler()
    out = []
    i = 0
    while i < len(blob):
        n = rng.randint(1, 97)
        asm.feed(blob[i:i + n])
        i += n
        while True:
            f = asm.next_frame()
            if f is None:
                break
            out.append(f)
    asm.check_eof()
    return out, None


def test_assembler_byte_parity_random_chunkings():
    rng = random.Random(7)
    frames = [
        (protocol.ACT, 1, bytes(rng.getrandbits(8) for _ in range(24))),
        (protocol.HEALTHZ, 2, b""),
        (protocol.ACT_OK, 3, bytes(rng.getrandbits(8) for _ in range(1 << 12))),
        (protocol.FEEDBACK_OK, 0xFFFFFFFF, b"x"),
        (protocol.OVERLOADED, 0, b"fd_exhausted"),
    ]
    blob = b"".join(encode_frame(*f) for f in frames)
    ref, _ = _frames_via_read_frame(blob)
    assert ref == frames
    for seed in range(5):
        got, _ = _frames_via_assembler(blob, random.Random(seed))
        assert got == ref


@pytest.mark.parametrize(
    "blob",
    [
        b"XX" + encode_frame(protocol.ACT, 1, b"abc")[2:],       # bad magic
        HEADER.pack(MAGIC, 99, protocol.ACT, 1, 0),              # bad version
        HEADER.pack(MAGIC, PROTOCOL_VERSION, protocol.ACT, 1,
                    MAX_PAYLOAD + 1),                            # oversized
        encode_frame(protocol.ACT, 1, b"abcdef")[:-3],           # torn payload
        encode_frame(protocol.ACT, 1, b"abc")[:7],               # torn header
        encode_frame(protocol.ACT2, 9, b"full") + HEADER.pack(
            MAGIC, PROTOCOL_VERSION, protocol.ACT, 2, 64),       # EOF at payload
    ],
    ids=["bad-magic", "bad-version", "oversized", "torn-payload",
         "torn-header", "eof-before-payload"],
)
def test_assembler_error_parity(blob):
    """Malformed streams: the assembler raises EXACTLY the ProtocolError
    read_frame raises — wording included (clients parse these)."""
    try:
        _frames_via_read_frame(blob)
        ref_msg = None
    except ProtocolError as e:
        ref_msg = str(e)
    assert ref_msg is not None, "fixture is not actually malformed"
    with pytest.raises(ProtocolError) as exc:
        _frames_via_assembler(blob, random.Random(3))
    assert str(exc.value) == ref_msg


def test_oversized_frame_rejected_before_payload_buffered():
    """A declared-oversize frame dies at header time: the assembler never
    holds a byte of its payload (memory-bomb resistance)."""
    asm = FrameAssembler()
    asm.feed(HEADER.pack(MAGIC, PROTOCOL_VERSION, protocol.ACT, 1,
                         MAX_PAYLOAD + 1))
    with pytest.raises(ProtocolError, match="payload length"):
        asm.next_frame()


# ------------------------------------------------------------ loop round-trip
def test_loop_echo_roundtrip_blocking_client():
    """The loop speaks the existing protocol byte-identically: the
    unchanged BLOCKING client primitives (write_frame/read_frame) work
    against it, pipelining included."""
    with _EchoLoop() as srv:
        with srv.connect() as c:
            sent = [
                (protocol.ACT, 1, b"\x00" * 24),
                (protocol.HEALTHZ, 2, b""),
                (protocol.ACT2, 3, bytes(range(256)) * 64),
            ]
            for f in sent:
                write_frame(c, *f)
            got = [read_frame(c) for _ in sent]
            assert got == sent
        # the client observing reply BYTES does not order it after the
        # loop thread's counter bump (send() returns first) — wait
        assert _deadline_wait(
            lambda: srv.loop.stats()["frames_out"] == 3
        ), srv.loop.stats()
        stats = srv.loop.stats()
        assert stats["frames_in"] == 3
        assert stats["conns_total"] == 1
        assert _deadline_wait(lambda: srv.loop.stats()["conns_open"] == 0)


def test_protocol_error_replies_then_closes():
    """Framing violation: ERROR frame (req_id 0, read_frame's wording)
    then FIN — and ONLY that connection dies."""
    with _EchoLoop() as srv:
        good = srv.connect()
        bad = srv.connect()
        bad.sendall(b"XX" + b"\x00" * 14)
        assert read_frame(bad) == (protocol.ERROR, 0, b"bad magic b'XX'")
        assert read_frame(bad) is None  # FIN after the notice
        bad.close()
        # the sibling connection is untouched
        write_frame(good, protocol.ACT, 7, b"still here")
        assert read_frame(good) == (protocol.ACT, 7, b"still here")
        good.close()


# ------------------------------------------------------------------ slowloris
def test_slowloris_partial_frame_evicted():
    with _EchoLoop(read_stall_s=0.3) as srv:
        c = srv.connect()
        c.sendall(encode_frame(protocol.ACT, 1, b"\x00" * 64)[:10])
        t, r, p = read_frame(c)
        assert (t, r) == (protocol.ERROR, 0)
        assert p.startswith(b"read stall")
        assert read_frame(c) is None
        c.close()
        assert srv.loop.stats()["evicted_read_stall"] == 1


def test_slowloris_trickle_never_resets_deadline():
    """The deadline is a frame-COMPLETION deadline: a drip of header
    bytes (progress, but never a frame) cannot push it out."""
    with _EchoLoop(read_stall_s=0.5) as srv:
        c = srv.connect()
        frame = encode_frame(protocol.ACT, 1, b"\x00" * 512)
        t0 = time.monotonic()
        evicted = threading.Event()

        def drip():
            for b in frame[:-1]:  # one byte short: can never complete
                if evicted.is_set():
                    return
                try:
                    c.sendall(bytes([b]))
                except OSError:
                    return
                time.sleep(0.005)

        th = threading.Thread(target=drip, name="test-drip", daemon=True)
        th.start()
        t, r, p = read_frame(c)
        evicted.set()
        elapsed = time.monotonic() - t0
        th.join(5)
        assert (t, r) == (protocol.ERROR, 0) and p.startswith(b"read stall")
        # evicted ~at the stall bound, NOT after len(frame)*5ms of drip
        assert elapsed < 2.0
        c.close()
        assert srv.loop.stats()["evicted_read_stall"] == 1


def test_pipeliner_with_partial_tail_not_evicted():
    """Completed frames re-arm the clock: a busy pipeliner whose buffer
    always ends in a partial frame outlives many stall windows."""
    with _EchoLoop(read_stall_s=0.3) as srv:
        c = srv.connect()
        full = encode_frame(protocol.ACT, 1, b"\x00" * 16)
        head = encode_frame(protocol.ACT, 2, b"\x00" * 16)
        n_rounds = 6  # ~1.2s total: 4x the stall bound
        for _ in range(n_rounds):
            c.sendall(full + head[:9])  # complete frame + torn tail
            assert read_frame(c) == (protocol.ACT, 1, b"\x00" * 16)
            c.sendall(head[9:])  # finish the tail...
            assert read_frame(c) == (protocol.ACT, 2, b"\x00" * 16)
            time.sleep(0.2)
        assert srv.loop.stats()["evicted_read_stall"] == 0
        c.close()


# ---------------------------------------------------------------- zero-window
def _tiny_sndbuf(conn):
    try:
        conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    except OSError:
        pass


def test_zero_window_watermark_evicts():
    """A peer that stops draining while replies pile up breaches the
    write-buffer watermark and is evicted immediately."""
    with _EchoLoop(on_open=_tiny_sndbuf, write_buffer_limit=1 << 16,
                   write_stall_s=30.0) as srv:
        c = srv.connect()
        c.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        # 64 echoed frames x 16KiB ~ 1MiB of replies nobody reads
        blob = b"".join(
            encode_frame(protocol.ACT, i, b"\x00" * (1 << 14))
            for i in range(64)
        )
        c.sendall(blob)
        assert _deadline_wait(
            lambda: srv.loop.stats()["evicted_write_stall"] >= 1
        ), srv.loop.stats()
        assert _deadline_wait(lambda: srv.loop.stats()["conns_open"] == 0)
        c.close()


def test_zero_window_write_stall_evicts():
    """Same attack, watermark out of reach: the write-progress deadline
    (the SO_SNDTIMEO contract, loop-owned) evicts instead."""
    with _EchoLoop(on_open=_tiny_sndbuf, write_stall_s=0.4) as srv:
        c = srv.connect()
        c.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        blob = b"".join(
            encode_frame(protocol.ACT, i, b"\x00" * (1 << 12))
            for i in range(16)
        )
        c.sendall(blob)
        assert _deadline_wait(
            lambda: srv.loop.stats()["evicted_write_stall"] >= 1
        ), srv.loop.stats()
        c.close()


# --------------------------------------------------------------- EMFILE shed
class _FlakyListener:
    """Wraps the real listener; the first ``fails`` accept() calls raise
    EMFILE — the descriptor-table-full mid-accept shape."""

    def __init__(self, real, fails):
        self._real = real
        self.fails = fails

    def accept(self):
        if self.fails > 0:
            self.fails -= 1
            raise OSError(errno.EMFILE, "Too many open files")
        return self._real.accept()

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_emfile_shed_not_die():
    """fd exhaustion mid-accept: the waiting client gets an explicit
    OVERLOADED fd_exhausted (via the burned reserve fd), and the NEXT
    client is served normally — the loop never dies."""
    with _EchoLoop() as srv:
        srv.loop._listener = _FlakyListener(srv.loop._listener, fails=1)
        shed = srv.connect()
        assert read_frame(shed) == (protocol.OVERLOADED, 0, b"fd_exhausted")
        assert read_frame(shed) is None
        shed.close()
        assert _deadline_wait(
            lambda: srv.loop.stats()["accept_shed"] == 1
        ), srv.loop.stats()
        ok = srv.connect()
        write_frame(ok, protocol.ACT, 1, b"after the storm")
        assert read_frame(ok) == (protocol.ACT, 1, b"after the storm")
        ok.close()


def test_emfile_with_no_reserve_pauses_accept_briefly():
    """Reserve fd already gone AND the table still full: the loop backs
    off the listener instead of spinning, then resumes."""
    with _EchoLoop() as srv:
        srv.loop._listener = _FlakyListener(srv.loop._listener, fails=1)
        # burn the reserve from outside the loop thread (test-only poke)
        import os

        fd, srv.loop._reserve_fd = srv.loop._reserve_fd, None
        if fd is not None:
            os.close(fd)
        c = srv.connect()
        # the one accept failure with no reserve -> backoff; after the
        # pause the (recovered) listener accepts and echoes normally
        assert _deadline_wait(
            lambda: srv.loop.stats()["accept_backoffs"] >= 1
        ), srv.loop.stats()
        write_frame(c, protocol.ACT, 1, b"resumed")
        assert read_frame(c) == (protocol.ACT, 1, b"resumed")
        c.close()


# -------------------------------------------------------------------- drain
def test_drain_answers_admitted_sheds_new():
    """stop_accepting(): the listener closes (new connects refused) while
    every open connection keeps being served; close() then flushes and
    FINs them."""
    with _EchoLoop() as srv:
        admitted = srv.connect()
        write_frame(admitted, protocol.ACT, 1, b"pre-drain")
        assert read_frame(admitted) == (protocol.ACT, 1, b"pre-drain")
        srv.loop.stop_accepting()
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", srv.port), timeout=0.5)
        # the admitted connection is still first-class
        write_frame(admitted, protocol.ACT, 2, b"mid-drain")
        assert read_frame(admitted) == (protocol.ACT, 2, b"mid-drain")
        srv.loop.close(flush_timeout_s=2.0)
        assert read_frame(admitted) is None  # clean FIN, nothing dropped
        admitted.close()
        assert srv.loop.stats()["conns_open"] == 0


def test_close_idempotent_and_never_started():
    loop = FrameLoop(name="test-idle")
    sock = socket.create_server(("127.0.0.1", 0))
    loop.serve(sock, on_frame=lambda *a: None)
    loop.close()  # never started: direct teardown, no hang
    loop.close()  # and again
    with _EchoLoop() as srv:
        srv.loop.close(flush_timeout_s=1.0)
        srv.loop.close(flush_timeout_s=1.0)
        assert not srv.loop._thread.is_alive()


def test_send_after_teardown_returns_false():
    """The dropped-reply contract: send() on a dead connection returns
    False (the caller books dropped_replies), never raises."""
    seen = []
    with _EchoLoop(on_open=seen.append) as srv:
        c = srv.connect()
        write_frame(c, protocol.ACT, 1, b"hello")
        assert read_frame(c) == (protocol.ACT, 1, b"hello")
        c.close()
        assert _deadline_wait(lambda: srv.loop.stats()["conns_open"] == 0)
        (conn,) = seen
        assert conn.send(protocol.ACT_OK, 1, b"too late") is False


# ----------------------------------------------------------- chaos attackers
def test_chaos_slowloris_attacker_gets_evicted():
    """The wired chaos site end-to-end: tick_attacks launches a loop-
    timer slowloris against the loop's own listener; the read-progress
    deadline evicts it while real traffic keeps flowing."""
    inj = chaos_mod.ChaosInjector(chaos_mod.ChaosPlan.parse("slowloris@1:200"))
    with _EchoLoop(read_stall_s=0.3) as srv:
        netio_attack.tick_attacks(inj, srv.loop, "127.0.0.1", srv.port)
        assert _deadline_wait(
            lambda: srv.loop.stats()["evicted_read_stall"] >= 1
        ), srv.loop.stats()
        # the service survived its attacker
        c = srv.connect()
        write_frame(c, protocol.ACT, 1, b"alive")
        assert read_frame(c) == (protocol.ACT, 1, b"alive")
        c.close()


def test_chaos_zero_window_attacker_gets_evicted():
    inj = chaos_mod.ChaosInjector(
        chaos_mod.ChaosPlan.parse("zero_window@1:6000")
    )
    with _EchoLoop(on_open=_tiny_sndbuf, write_stall_s=0.4,
                   write_buffer_limit=1 << 13) as srv:
        netio_attack.tick_attacks(inj, srv.loop, "127.0.0.1", srv.port)
        assert _deadline_wait(
            lambda: srv.loop.stats()["evicted_write_stall"] >= 1
        ), srv.loop.stats()


def test_chaos_sites_registered():
    for site in ("slowloris", "zero_window", "fd_exhaust"):
        assert site in chaos_mod.KNOWN_SITES


def test_reply_guard_configures_so_sndtimeo():
    """Satellite: the ONE shared SO_SNDTIMEO guard for thread-path
    endpoints (fleet ingest) — both copies in serve/router are gone."""
    from d4pg_tpu.netio import configure_reply_timeout

    a, b = socket.socketpair()
    try:
        configure_reply_timeout(a, timeout_s=3.0)
        tv = a.getsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, 16)
        import struct as _struct

        sec, _usec = _struct.unpack("ll", tv)
        assert sec == 3
    finally:
        a.close()
        b.close()
