"""Tier-1 smokes for the multi-tenant serving microbench.

Two halves, mirroring the other benchmark smokes:

- the GENERATOR runs end-to-end at a tiny shape. The isolation claim is
  asserted even here — bulk-first shed protecting interactive p99 is a
  correctness contract of the admission tier, not a performance number —
  as is the per-(tenant, class) accounting identity; the scaling RATIO is
  only pinned on the committed artifact (CPU noise at tiny shapes). The
  smoke also enforces the tier-1 clock budget this suite declared
  (ISSUE-12 satellite): the whole generator leg must stay under
  ``FAST_BUDGET_S``.
- the COMMITTED artifact (``benchmarks/multitenant_microbench.json``)
  keeps its schema and the acceptance headlines: a flooding bulk tenant
  cannot move interactive p99 past its SLO (``isolation_ok``), the
  accounting identity is exact per tenant/class, and aggregate rps
  scales with the autoscaled replica count. Regenerate:
  ``JAX_PLATFORMS=cpu python benchmarks/multitenant_microbench.py``.
"""

from __future__ import annotations

import json
import os
import time

import pytest

pytest.importorskip("jax")

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "multitenant_microbench.json",
)

# The stated fast-tier budget for this suite's generator leg (the tier-1
# clock guard satellite): the gate has ~310 s of headroom and this suite
# must not eat it. Measured ~12 s on the 2-core CI box; 60 s is the
# hard line past which this belongs behind the slow marker instead.
FAST_BUDGET_S = 60.0


def test_generator_runs_at_small_shape_within_budget(tmp_path):
    from benchmarks.multitenant_microbench import run_microbench

    t0 = time.monotonic()
    out_path = str(tmp_path / "multitenant_microbench.json")
    out = run_microbench(
        out_path,
        hidden=8,
        max_batch=8,
        duration_s=0.8,
        infer_delay_ms=30.0,
        replica_capacity=12,
        scale_window_s=0.6,
        repeats=1,
    )
    elapsed = time.monotonic() - t0
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "multitenant_microbench"
    iso = out["isolation"]
    # correctness at ANY scale: the flood is shed bulk-first, interactive
    # stays inside its SLO, and nothing is silently lost anywhere
    assert iso["isolation_ok"] is True
    assert iso["tenant_identity_ok"] is True
    assert iso["router_identity_ok"] is True
    assert iso["bulk_shed_rate"] > 0.1  # the flood really overloaded
    assert iso["shed_bulk_capacity"] > 0  # ...and bulk shed at ITS line
    for key, row in iso["tenants"].items():
        assert row["requests"] == row["answered"], (key, row)
    scal = out["autoscale_scaling"]
    assert scal["identity_ok"] is True
    assert scal["admitted_after_scale"] == 2 and scal["scale_ups"] == 1
    assert elapsed < FAST_BUDGET_S, (
        f"multitenant microbench smoke took {elapsed:.1f}s — past the "
        f"stated {FAST_BUDGET_S:.0f}s fast-tier budget; shrink the shape "
        "or move it behind the slow marker"
    )


def test_committed_artifact_meets_acceptance():
    with open(ARTIFACT) as f:
        art = json.load(f)
    assert art["metric"] == "multitenant_microbench"
    assert art["backend"] == "cpu"  # chip-independent artifact
    iso = art["isolation"]
    # THE isolation headline: the flooding bulk tenant could not move the
    # interactive tier's p99 past its SLO...
    assert iso["isolation_ok"] is True
    assert iso["interactive_p99_ms"] <= iso["slo_ms"]
    # ...while the flood was REAL (bulk overwhelmingly shed, at the bulk
    # capacity line, not the interactive one)
    assert iso["bulk_shed_rate"] >= 0.5
    assert iso["shed_bulk_capacity"] > 0
    assert iso["tenant_identity_ok"] is True
    assert iso["router_identity_ok"] is True
    # aggregate rps scales with the autoscaled replica count
    scal = art["autoscale_scaling"]
    assert scal["scaling_2_over_1"] >= 1.3
    assert scal["rps_2_replicas"] > scal["rps_1_replica"]
    assert scal["admitted_after_scale"] == 2
    assert scal["scale_ups"] >= 1
    assert scal["identity_ok"] is True
    # the slow-device stub must stay labeled (the regime claim depends
    # on it — see the generator docstring)
    assert art["infer_delay_ms"] > 0
    assert len(art["ratio_repeats"]) == art["repeats"]
