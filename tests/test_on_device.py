"""Fully on-device training loop: correctness + it learns Pendulum signal."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.agent import D4PGConfig, create_train_state
from d4pg_tpu.envs import Pendulum
from d4pg_tpu.models.critic import DistConfig
from d4pg_tpu.runtime.on_device import make_on_device_trainer
from d4pg_tpu.ops import nstep_returns


def test_nstep_truncation_stops_window_keeps_bootstrap():
    rewards = jnp.ones(6)
    dones = jnp.zeros(6)
    truncs = jnp.asarray([0.0, 0.0, 1.0, 0.0, 0.0, 0.0])
    r, b, m = nstep_returns(rewards, dones, 0.5, 3, truncations=truncs)
    # window at t=1 stops after step 2 (truncation): m=2, bootstrap kept
    np.testing.assert_array_equal(np.asarray(m), [3, 2, 1, 3, 2, 1])
    np.testing.assert_allclose(np.asarray(b[1]), 0.25, atol=1e-6)
    np.testing.assert_allclose(np.asarray(b[2]), 0.5, atol=1e-6)
    # rewards never cross the truncation into the next episode
    np.testing.assert_allclose(np.asarray(r[1]), 1 + 0.5, atol=1e-6)


@pytest.mark.slow
def test_on_device_iteration_shapes_and_replay_fill():
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(32, 32),
        dist=DistConfig(num_atoms=21, v_min=-300, v_max=0), n_step=3,
    )
    env = Pendulum()
    init_fn, _warmup_fn, iterate_fn = make_on_device_trainer(
        config, env, num_envs=4, segment_len=16,
        replay_capacity=1024, batch_size=32, train_steps_per_iter=4,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    carry = init_fn(state, jax.random.PRNGKey(1))
    for i in range(3):
        carry, metrics = iterate_fn(carry, 1.0)
    state2, _, _, _, replay, _ = carry
    assert int(replay.size) == 3 * 4 * 16
    assert int(state2.step) == 3 * 4
    assert np.isfinite(float(metrics["critic_loss"]))
    # ring buffer rows written with valid discounts in [0, 1]
    d = np.asarray(replay.discount[: int(replay.size)])
    assert np.all((d >= 0) & (d <= 1))


def test_on_device_capacity_validation():
    config = D4PGConfig(obs_dim=3, action_dim=1)
    with pytest.raises(ValueError):
        make_on_device_trainer(
            config, Pendulum(), num_envs=3, segment_len=10, replay_capacity=1000
        )


@pytest.mark.slow
def test_on_device_learns_pendulum_signal():
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(64, 64),
        dist=DistConfig(num_atoms=51, v_min=-300, v_max=0),
        n_step=3, tau=0.005, lr_actor=5e-4, lr_critic=5e-4,
    )
    env = Pendulum()
    init_fn, _warmup_fn, iterate_fn = make_on_device_trainer(
        config, env, num_envs=16, segment_len=32,
        replay_capacity=65_536, batch_size=128, train_steps_per_iter=64,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    carry = init_fn(state, jax.random.PRNGKey(1))
    losses = []
    for i in range(150):
        carry, metrics = iterate_fn(carry, 1.0)
        losses.append(float(metrics["critic_loss"]))
    from d4pg_tpu.runtime import evaluate

    trained = evaluate(config, env, carry[0].actor_params, jax.random.PRNGKey(7), 10)
    base_state = create_train_state(config, jax.random.PRNGKey(123))
    base = evaluate(config, env, base_state.actor_params, jax.random.PRNGKey(7), 10)
    assert trained["eval_return_mean"] > base["eval_return_mean"] + 250
    assert losses[-1] < losses[2]


@pytest.mark.slow
def test_on_device_prioritized_sampling_and_updates():
    """Device PER: cumsum+searchsorted sampling is proportional, priorities
    update after the train scan, new rows seed at max_priority^alpha."""
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(32, 32),
        dist=DistConfig(num_atoms=21, v_min=-300, v_max=0), n_step=3,
        prioritized=True,
    )
    env = Pendulum()
    init_fn, _warmup_fn, iterate_fn = make_on_device_trainer(
        config, env, num_envs=4, segment_len=16,
        replay_capacity=1024, batch_size=32, train_steps_per_iter=4,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    carry = init_fn(state, jax.random.PRNGKey(1))
    carry, m1 = iterate_fn(carry, 1.0)
    _, _, _, _, replay, _ = carry
    n = int(replay.size)
    pr = np.asarray(replay.priority)
    # filled rows have nonzero priority, unfilled are exactly zero
    assert np.all(pr[:n] > 0) and np.all(pr[n:] == 0)
    # trained-on rows got real (non-seed) priorities: not all equal
    assert np.unique(pr[:n]).size > 1
    carry, m2 = iterate_fn(carry, 1.0)
    assert np.isfinite(float(m2["critic_loss"]))
    assert float(carry[4].max_priority) >= 1.0


def test_device_per_proportional_statistics():
    """Sampling frequency tracks priority mass: a slot with 9x the priority
    of the rest is drawn ~9x more often."""
    import jax.numpy as jnp
    from d4pg_tpu.runtime.on_device import DeviceReplay, device_replay_init

    C = 256
    replay = device_replay_init(C, 3, 1)
    prio = np.full(C, 1.0, np.float32)
    prio[7] = 9.0 * (C - 1) / 1.0  # slot 7 carries 90% of the mass
    replay = replay._replace(
        priority=jnp.asarray(prio), size=jnp.asarray(C, jnp.int32)
    )
    cums = jnp.cumsum(replay.priority)
    u = jax.random.uniform(jax.random.PRNGKey(0), (20_000,)) * cums[-1]
    idx = np.asarray(jnp.clip(jnp.searchsorted(cums, u), 0, C - 1))
    frac = (idx == 7).mean()
    assert 0.88 < frac < 0.92


@pytest.mark.slow
def test_run_on_device_cli_driver(tmp_path):
    """train.py --on-device end-to-end: the run_on_device periphery (eval,
    EWMA, metrics files, checkpoints, resume) around the fused loop."""
    import json
    import os

    from train import config_from_args, build_parser

    argv = [
        "--env", "pendulum", "--on-device", "--num-envs", "2",
        "--total-steps", "8", "--eval-interval", "4",
        "--eval-episodes", "2", "--checkpoint-interval", "8",
        "--env-steps-per-train-step", "16",  # 2 envs × 32 seg / 16 = 4 steps/iter
        "--bsize", "32", "--rmsize", "256", "--warmup", "0",
        "--log-dir", str(tmp_path / "run"),
    ]
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    from d4pg_tpu.runtime.on_device import run_on_device

    out = run_on_device(cfg)
    assert np.isfinite(out["critic_loss"])
    assert "eval_return_mean" in out and "avg_test_reward_ewma" in out
    lines = [
        json.loads(l)
        for l in open(tmp_path / "run" / "metrics.jsonl")
    ]
    assert lines and lines[-1]["step"] == 8
    assert os.path.isdir(tmp_path / "run" / "checkpoints")
    # resume restores the step counter; --total-steps is a PER-INVOCATION
    # budget (matches Trainer.train and the supervisor recipe): 8 restored
    # + 8 more = 16
    cfg2 = config_from_args(build_parser().parse_args(argv + ["--resume"]))
    out2 = run_on_device(cfg2)
    lines = [
        json.loads(l)
        for l in open(tmp_path / "run" / "metrics.jsonl")
    ]
    assert lines[-1]["step"] == 16


@pytest.mark.slow
def test_on_device_dp_over_mesh():
    """Distributed fully-on-device loop (config 5 at pod scale): envs,
    replay shards and batch split over the 8-device mesh, grads pmean'd,
    params replicated and identical; global sizes divide across the axis;
    training shows a learning signal."""
    from d4pg_tpu.parallel import make_mesh

    mesh = make_mesh(dp=8, tp=1)
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(32, 32), n_step=2,
        lr_actor=1e-3, lr_critic=1e-3,
        dist=DistConfig(kind="categorical", num_atoms=21, v_min=-200.0, v_max=0.0),
    )
    init_fn, warmup_fn, iterate_fn = make_on_device_trainer(
        config, Pendulum(),
        num_envs=16, segment_len=8, replay_capacity=2048,
        batch_size=64, train_steps_per_iter=4, mesh=mesh,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    from d4pg_tpu.parallel.dp import replicate

    carry = init_fn(replicate(state, mesh), jax.random.PRNGKey(1))
    carry = warmup_fn(carry, 1.0)
    losses = []
    for _ in range(8):
        carry, m = iterate_fn(carry, 1.0)
        losses.append(float(m["critic_loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # distributional CE collapses from init
    new_state, *_, replay, _key = carry
    # replay ring is sharded: global leading dim = full capacity, each of
    # the 8 shards advanced identically
    assert replay.obs.shape[0] == 2048
    assert int(replay.size) > 0
    # params stayed replicated AND identical across devices
    leaf = jax.tree.leaves(new_state.actor_params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    # step advanced once per grad step regardless of device count
    assert int(jax.device_get(new_state.step)) == (1 + 8) * 4 - 4  # warmup trains 0


@pytest.mark.slow
def test_run_on_device_cli_driver_dp(tmp_path):
    """--on-device --dp 8: the CLI driver runs the distributed loop."""
    from train import build_parser, config_from_args
    from d4pg_tpu.runtime.on_device import run_on_device

    argv = [
        "--env", "pendulum", "--on-device", "--dp", "8", "--num-envs", "8",
        "--total-steps", "8", "--eval-interval", "8", "--eval-episodes", "2",
        "--checkpoint-interval", "1000000",
        "--env-steps-per-train-step", "64",  # 8 envs × 32 seg / 64 = 4/iter
        "--bsize", "64", "--rmsize", "1024", "--warmup", "0",
        "--log-dir", str(tmp_path / "run"),
    ]
    out = run_on_device(config_from_args(build_parser().parse_args(argv)))
    assert np.isfinite(out["critic_loss"])
    assert "eval_return_mean" in out


def test_on_device_uint8_obs_ring():
    """Pixel-style [0,1] obs store uint8 in the device ring and decode to
    within quantization error on the training path."""
    import jax.numpy as jnp
    from d4pg_tpu.runtime.on_device import (
        _append,
        _decode_obs,
        device_replay_init,
    )

    replay = device_replay_init(64, 8, 1, obs_dtype=jnp.uint8)
    assert replay.obs.dtype == jnp.uint8
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.uniform(0, 1, (16, 8)), jnp.float32)
    batch = {
        "obs": obs,
        "action": jnp.zeros((16, 1)),
        "reward": jnp.zeros((16,)),
        "next_obs": obs,
        "discount": jnp.full((16,), 0.99),
    }
    replay = _append(replay, batch, 16, alpha=0.6)
    decoded = _decode_obs(replay.obs[:16], jnp.uint8)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(obs), atol=1 / 255)


def test_on_device_bf16_obs_ring():
    """--ring-dtype bfloat16 (flat obs): rows store at half the HBM bytes
    and decode back to f32 within bf16 mantissa error (~0.4% relative);
    the factory rejects uint8+bf16 together."""
    import jax.numpy as jnp
    from d4pg_tpu.envs import Pendulum
    from d4pg_tpu.runtime.on_device import (
        _append,
        _decode_obs,
        device_replay_init,
        make_on_device_trainer,
    )
    from d4pg_tpu.agent import D4PGConfig

    replay = device_replay_init(64, 8, 1, obs_dtype=jnp.bfloat16)
    assert replay.obs.dtype == jnp.bfloat16
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    batch = {
        "obs": obs,
        "action": jnp.zeros((16, 1)),
        "reward": jnp.zeros((16,)),
        "next_obs": obs,
        "discount": jnp.full((16,), 0.99),
    }
    replay = _append(replay, batch, 16, alpha=0.6)
    decoded = _decode_obs(replay.obs[:16], jnp.bfloat16)
    assert decoded.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(decoded), np.asarray(obs), rtol=8e-3, atol=1e-6
    )
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_on_device_trainer(
            D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16)),
            Pendulum(), num_envs=2, segment_len=8, replay_capacity=64,
            batch_size=8, obs_uint8=True, obs_bf16=True,
        )


@pytest.mark.slow
def test_on_device_bf16_ring_trains(tmp_path):
    """run_on_device with --ring-dtype bfloat16 trains to finite losses
    through the CLI config path (the bf16 decode feeds the train scan)."""
    import dataclasses

    from train import build_parser, config_from_args
    from d4pg_tpu.runtime.on_device import run_on_device

    argv = [
        "--env", "pendulum", "--on-device", "--ring-dtype", "bfloat16",
        "--num-envs", "2", "--total-steps", "2", "--eval-interval", "2",
        "--eval-episodes", "1", "--checkpoint-interval", "1000000",
        "--max-steps", "24", "--env-steps-per-train-step", "32",
        "--bsize", "16", "--rmsize", "128", "--warmup", "0",
        "--log-dir", str(tmp_path / "bf16ring"),
    ]
    cfg = config_from_args(build_parser().parse_args(argv))
    cfg = dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, hidden_sizes=(32, 32))
    )
    out = run_on_device(cfg)
    assert np.isfinite(out["critic_loss"])


@pytest.mark.slow
def test_on_device_pixel_trainer_uint8(tmp_path, monkeypatch):
    """run_on_device on the pixel env: the uint8 ring path is actually
    engaged (factory receives obs_uint8=True, scale 255) and a training
    iteration is finite."""
    import dataclasses

    import d4pg_tpu.runtime.on_device as od
    from train import build_parser, config_from_args
    from d4pg_tpu.runtime.on_device import run_on_device

    argv = [
        "--env", "pixel_pendulum", "--on-device", "--num-envs", "2",
        "--total-steps", "2", "--eval-interval", "2", "--eval-episodes", "1",
        "--checkpoint-interval", "1000000", "--max-steps", "24",
        "--env-steps-per-train-step", "32",
        "--bsize", "16", "--rmsize", "128", "--warmup", "0",
        "--log-dir", str(tmp_path / "run"),
    ]
    cfg = config_from_args(build_parser().parse_args(argv))
    cfg = dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, hidden_sizes=(32, 32))
    )
    captured = {}
    orig = od.make_on_device_trainer

    def spy(*a, **kw):
        captured.update(kw)
        return orig(*a, **kw)

    monkeypatch.setattr(od, "make_on_device_trainer", spy)
    out = run_on_device(cfg)
    assert np.isfinite(out["critic_loss"])
    assert captured["obs_uint8"] is True and captured["obs_scale"] == 255.0


@pytest.mark.slow
def test_on_device_rss_watchdog(tmp_path):
    """--max-rss-gb works in --on-device mode too: a tiny limit preempts at
    the first eval crossing with a checkpoint and the _preempted marker."""
    import dataclasses
    import os

    from train import build_parser, config_from_args
    from d4pg_tpu.runtime.on_device import run_on_device

    argv = [
        "--env", "pendulum", "--on-device", "--num-envs", "2",
        "--total-steps", "64", "--eval-interval", "4", "--eval-episodes", "1",
        "--checkpoint-interval", "1000000",
        "--env-steps-per-train-step", "16",
        "--bsize", "32", "--rmsize", "256", "--warmup", "0",
        "--log-dir", str(tmp_path / "run"),
    ]
    cfg = config_from_args(build_parser().parse_args(argv))
    cfg = dataclasses.replace(cfg, max_rss_gb=0.001)
    out = run_on_device(cfg)
    assert out.get("_preempted") is True
    assert os.path.isdir(tmp_path / "run" / "checkpoints")
