"""Runtime invariant guards (d4pg_tpu/analysis): each guard must (a) stay
silent on the clean path and (b) catch a deliberately injected violation
with an attributable error — the clean half alone would prove nothing.

Covers the ISSUE-4 acceptance matrix: recompile sentinel (training
regression, prefetch on AND off, plus an injected shape-drift trip),
transfer guard (clean trainer/batcher dispatch, plus an injected
implicit-transfer trip), staging ledger (unit semantics, replay
sample_block rotation stress, serve batcher slow-device stress with the
PR-3 "unbounded in-flight" bug seeded behind a test hook), and the
--debug-guards integration smoke (all guards on, zero trips).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from d4pg_tpu.analysis import (
    RecompileBudgetError,
    RecompileSentinel,
    StagingLedger,
    StagingReuseError,
    no_implicit_transfers,
)
from d4pg_tpu.analysis.ledger import NULL_LEDGER


# ----------------------------------------------------------------- ledger unit
def test_ledger_write_hold_release_cycle():
    led = StagingLedger("t")
    assert led.write("g", 0) == 1
    h = led.hold("g", 0, holder="dispatch#1")
    h.release()
    assert led.write("g", 0) == 2  # released hold: rewrite fine
    assert led.stats()["trips"] == 0


def test_ledger_trips_on_write_while_held_naming_slot_and_holder():
    led = StagingLedger("replay")
    led.write("per.sample_block[n=64]", 1, writer="sampler")
    led.hold("per.sample_block[n=64]", 1, holder="dispatch#7")
    with pytest.raises(StagingReuseError) as ei:
        led.write("per.sample_block[n=64]", 1, writer="sampler")
    msg = str(ei.value)
    assert "per.sample_block[n=64]" in msg and "[1]" in msg  # the slot
    assert "dispatch#7" in msg                               # the holder
    assert "sampler" in msg                                  # the writer
    assert led.stats()["trips"] == 1


def test_ledger_release_is_idempotent_and_null_ledger_is_free():
    led = StagingLedger("t")
    led.write("g", 0)
    h = led.hold("g", 0)
    h.release()
    h.release()
    led.write("g", 0)
    # null ledger: everything is a no-op, never raises
    NULL_LEDGER.write("g", 0)
    NULL_LEDGER.hold("g", 0).release()
    assert NULL_LEDGER.stats()["trips"] == 0


# ------------------------------------------------------- ledger: replay staging
def _tiny_per_buffer(ledger=None, slots=None):
    from d4pg_tpu.replay import PrioritizedReplayBuffer, Transition

    buf = PrioritizedReplayBuffer(256, 3, 1, tree_backend="numpy")
    if slots is not None:
        buf.STAGING_SLOTS = slots  # instance override: the test hook
    if ledger is not None:
        buf.set_ledger(ledger)
    n = 64
    rng = np.random.default_rng(0)
    buf.add_batch(
        Transition(
            obs=rng.standard_normal((n, 3)).astype(np.float32),
            action=rng.standard_normal((n, 1)).astype(np.float32),
            reward=np.zeros(n, np.float32),
            next_obs=rng.standard_normal((n, 3)).astype(np.float32),
            discount=np.ones(n, np.float32),
        )
    )
    return buf


def test_sample_block_ledger_clean_with_prompt_releases():
    led = StagingLedger("replay")
    buf = _tiny_per_buffer(ledger=led)
    rng = np.random.default_rng(1)
    holds = []
    for _ in range(10):  # well past the 3-slot rotation
        out = buf.sample_block(8, 2, rng)
        holds.append(out.pop("_staging_hold"))
        while len(holds) > 2:  # trainer contract: ≤2 dispatches in flight
            holds.pop(0).release()
    assert led.stats()["trips"] == 0
    assert led.stats()["writes"] == 10


def test_sample_block_ledger_catches_late_consumer_past_rotation():
    """Seeded bug: a consumer that holds staged batches longer than the
    rotation depth (the PR-2 class: async dispatch outliving the slots)."""
    led = StagingLedger("replay")
    buf = _tiny_per_buffer(ledger=led)
    rng = np.random.default_rng(1)
    holds = [buf.sample_block(8, 2, rng).pop("_staging_hold")
             for _ in range(buf.STAGING_SLOTS)]  # all 3 slots held
    with pytest.raises(StagingReuseError) as ei:
        buf.sample_block(8, 2, rng)  # wraps onto slot 0, still held
    assert "per.sample_block[n=16]" in str(ei.value)
    assert holds[0].released is False


def test_sample_block_ledger_catches_shrunken_rotation():
    """Seeded bug via the test hook: STAGING_SLOTS=1 (no rotation at all)
    with a normally-paced consumer trips on the second sample."""
    led = StagingLedger("replay")
    buf = _tiny_per_buffer(ledger=led, slots=1)
    rng = np.random.default_rng(1)
    out = buf.sample_block(8, 1, rng)
    _hold = out.pop("_staging_hold")  # dispatch in flight, never released
    with pytest.raises(StagingReuseError):
        buf.sample_block(8, 1, rng)


def test_sample_block_without_ledger_has_no_hold_key():
    buf = _tiny_per_buffer()
    out = buf.sample_block(8, 2, np.random.default_rng(1))
    assert "_staging_hold" not in out  # guards-off behavior is unchanged


# ------------------------------------------------------------------- sentinel
def test_sentinel_counts_and_budget_trip_on_shape_drift():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2)
    with RecompileSentinel() as sen:
        sen.track("f", f)
        before = sen.total_compiles
        f(jnp.ones(3))
        assert sen.count("f") == 1
        assert sen.total_compiles > before  # global stream sees it too
        sen.freeze()  # budget: what warmup compiled
        f(jnp.ones(3))
        sen.check("steady")  # cache hit: fine
        f(jnp.ones(4))  # injected violation: a shape drifted
        with pytest.raises(RecompileBudgetError) as ei:
            sen.check("steady")
    assert "f: 2 compiles > budget 1" in str(ei.value)


def test_sentinel_explicit_budget_and_unbudgeted_entries():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    g = jax.jit(lambda x: x - 1)
    sen = RecompileSentinel()
    sen.track("f", f, budget=2)
    sen.track("g", g)  # unbudgeted: never checked until frozen
    f(jnp.ones(2))
    f(jnp.ones(3))
    g(jnp.ones(2))
    g(jnp.ones(3))
    sen.check()  # f within its explicit budget, g unbudgeted
    sen.set_budget("f", 1)
    with pytest.raises(RecompileBudgetError):
        sen.check()


# -------------------------------------------------------------- transfer guard
def test_transfer_guard_catches_implicit_transfer_and_exempts_device_put():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2)
    host = np.ones(3, np.float32)
    dev = jax.device_put(host)
    f(dev)  # warmup outside the guard
    with no_implicit_transfers():
        f(dev)  # device operand: clean
        jax.device_put(host)  # explicit transfer: exempt by design
        with pytest.raises(Exception, match="[Dd]isallowed host-to-device"):
            f(host)  # injected violation: implicit numpy upload
    f(host)  # outside the guard: allowed again (context is scoped)


def test_transfer_guard_disabled_is_a_noop():
    import jax

    f = jax.jit(lambda x: x * 2)
    with no_implicit_transfers(enabled=False):
        np.asarray(f(np.ones(3, np.float32)))  # implicit transfer fine


# -------------------------------------------- batcher: slow-device stress test
class _GatedArray:
    """Device-output stub whose D2H fetch (np.asarray) blocks on an event:
    makes 'the reply thread is slower than the device thread' a
    deterministic fact instead of a race."""

    def __init__(self, value: np.ndarray, gate: threading.Event):
        self._value = value
        self._gate = gate

    def __array__(self, dtype=None, copy=None):
        self._gate.wait(10.0)
        return self._value if dtype is None else self._value.astype(dtype)


def _tiny_batcher(**kw):
    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.serve.batcher import DynamicBatcher
    from d4pg_tpu.serve.bundle import actor_template

    cfg = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(8, 8))
    return DynamicBatcher(
        cfg, actor_template(cfg), max_batch=2, max_wait_us=0, **kw
    )


def test_batcher_ledger_clean_under_slow_device_past_rotation():
    """Slow device, real backpressure (the 2-permit semaphore): many
    batches rotate through the 2 slots with zero ledger trips."""
    led = StagingLedger("serve")
    b = _tiny_batcher(ledger=led)
    orig = b._infer
    b._infer = lambda p, o: (time.sleep(0.005), orig(p, o))[1]  # slow stub
    b.start()
    try:
        futs = [b.submit(np.zeros(3, np.float32)) for _ in range(12)]
        for f in futs:
            f.result(timeout=30)
    finally:
        b.stop()
    assert led.stats()["trips"] == 0
    assert led.stats()["writes"] >= 6  # well past the 2-slot rotation


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_batcher_ledger_catches_seeded_inflight_bug():
    """Seeded bug behind a test hook: remove the in-flight bound (the
    PR-3 round-2 regression — staging rotation is only safe if the host
    can't run ahead) and gate the reply thread's D2H. The third dispatch
    wraps onto slot 0 while its hold is live → the ledger must name the
    slot and the holding dispatch."""
    led = StagingLedger("serve")
    b = _tiny_batcher(ledger=led)
    gate = threading.Event()
    b._inflight = threading.Semaphore(1000)  # the deliberate bug
    b._infer = lambda p, o: _GatedArray(
        np.zeros((np.asarray(o).shape[0], 1), np.float32), gate
    )
    b.start(warmup=False)
    try:
        futs = []
        for _ in range(6):  # one-request batches → ≥3 dispatches → reuse
            try:
                futs.append(b.submit(np.zeros(3, np.float32)))
            except RuntimeError:
                break  # device thread already died on the trip — enough
            time.sleep(0.05)  # let the device thread dispatch one-by-one
        gate.set()  # trip already happened; unblock the gated D2H fetches
        excs = []
        for f in futs:
            try:
                f.result(timeout=10)
            except Exception as e:  # noqa: BLE001 - collecting the trip
                excs.append(e)
        trips = [e for e in excs if isinstance(e, StagingReuseError)]
        assert trips, f"ledger never tripped; got {excs!r}"
        msg = str(trips[0])
        assert "serve.staging[" in msg        # the slot (bucket + index)
        assert "dispatch(n=" in msg           # the holder
        assert led.stats()["trips"] >= 1
    finally:
        gate.set()
        try:
            b.stop(timeout=5)
        except RuntimeError:
            pass  # device thread died on the trip — expected


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_batcher_force_flip_hook_defeats_rotation():
    """The simpler seeded bug: _test_force_flip pins the rotation to one
    slot; with the reply thread gated, the very next dispatch trips."""
    led = StagingLedger("serve")
    b = _tiny_batcher(ledger=led)
    gate = threading.Event()
    b._test_force_flip = 0  # the test hook: single-buffer the staging
    b._infer = lambda p, o: _GatedArray(
        np.zeros((np.asarray(o).shape[0], 1), np.float32), gate
    )
    b.start(warmup=False)
    try:
        futs = [b.submit(np.zeros(3, np.float32)) for _ in range(4)]
        time.sleep(0.2)  # both slots written → the pinned flip has tripped
        gate.set()
        excs = []
        for f in futs:
            try:
                f.result(timeout=10)
            except Exception as e:  # noqa: BLE001
                excs.append(e)
        assert any(isinstance(e, StagingReuseError) for e in excs)
    finally:
        gate.set()
        try:
            b.stop(timeout=5)
        except RuntimeError:
            pass  # dead device thread, as engineered


# ------------------------------------------- training regression + integration
def _guarded_config(tmp_path, tag, **kw):
    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.config import TrainConfig

    base = dict(
        env="pendulum",
        total_steps=4,
        warmup_steps=32,
        batch_size=16,
        num_envs=2,
        eval_interval=1000,
        checkpoint_interval=1000,
        debug_guards=True,
        log_dir=str(tmp_path / tag),
        agent=D4PGConfig(hidden_sizes=(16, 16)),
    )
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.parametrize("prefetch", [False, True])
def test_recompile_budget_flat_after_warmup(tmp_path, prefetch):
    """Satellite: short CPU run, prefetch on and off — train_step/act
    compile counts must not grow after the first dispatch, asserted by
    the sentinel (not the old ad-hoc serve-test stub). A second train()
    leg re-drives the whole loop against the frozen budget."""
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(_guarded_config(tmp_path, f"rc_{prefetch}", prefetch=prefetch))
    try:
        t.train()
        counts = t.sentinel.counts()
        assert counts["train_step"] == 1, counts
        t.train(total_steps=4)  # second leg: budgets already pinned
        after = t.sentinel.counts()
        assert after == counts, f"compile counts moved: {counts} -> {after}"
        t.sentinel.check("end of regression test")
        assert t._ledger.stats()["trips"] == 0
    finally:
        t.close()


def test_debug_guards_integration_smoke(tmp_path):
    """Acceptance: --debug-guards runs the integration smoke with zero
    guard trips — transfer guard wraps every dispatch, the ledger tags
    replay staging under prefetch, and the sentinel budget holds."""
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = _guarded_config(
        tmp_path, "smoke", prefetch=True, total_steps=6, eval_interval=3
    )
    t = Trainer(cfg)
    try:
        out = t.train()
        assert "eval_return_mean" in out
        assert t.sentinel.counts()["train_step"] == 1
        stats = t._ledger.stats()
        assert stats["trips"] == 0 and stats["writes"] >= 6
        assert not t._staging_holds  # all released at train() end
    finally:
        t.close()


def test_debug_guards_clean_across_resume(tmp_path):
    """Regression (chaos-soak fleet leg): Orbax restore hands back
    host-resident leaves; without the explicit post-restore device_put
    commit, the first guarded dispatch of a --resume --debug-guards run
    trips the transfer guard on the restored state's int32 step scalar."""
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(_guarded_config(tmp_path, "res", checkpoint_interval=4))
    try:
        t.train()
    finally:
        t.close()
    r = Trainer(_guarded_config(tmp_path, "res", resume=True, total_steps=8))
    try:
        r.train()  # without the commit this raises the disallowed-transfer
        assert r.grad_steps > 4  # really trained past the restored step
        assert r._ledger.stats()["trips"] == 0
    finally:
        r.close()


def test_guards_no_false_trip_with_lagging_async_flusher(tmp_path, monkeypatch):
    """The async priority flusher paces hold releases; a lagging flusher
    must make the guarded learner WAIT, not false-trip the ledger. The
    flusher is artificially slowed so the learner would rotate staging
    past held slots without the pacing loop in _sample_staged."""
    from d4pg_tpu.runtime.trainer import Trainer

    orig_start = Trainer._start_writeback

    def slow_start(self):
        orig_start(self)
        real_get = self._wb_queue.get

        def slow_get(*a, **kw):
            item = real_get(*a, **kw)
            time.sleep(0.05)  # the lag: learner outruns the release point
            return item

        self._wb_queue.get = slow_get

    monkeypatch.setattr(Trainer, "_start_writeback", slow_start)
    cfg = _guarded_config(
        tmp_path, "lagwb", prefetch=True, total_steps=10,
        async_priority_writeback=True,
    )
    t = Trainer(cfg)
    try:
        t.train()  # without the pacing wait this raises StagingReuseError
        assert t._ledger.stats()["trips"] == 0
        assert not t._staging_holds
    finally:
        t.close()


def test_train_cli_wires_debug_guards_flag():
    from train import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--env", "pendulum", "--debug-guards"]
    )
    assert config_from_args(args).debug_guards is True
    args = build_parser().parse_args(["--env", "pendulum"])
    assert config_from_args(args).debug_guards is False


def test_policy_server_debug_guards_end_to_end():
    """--debug-guards through the real server: ledger + sentinel + transfer
    guard active, traffic served, drain runs the bucket-budget check."""
    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.serve.bundle import PolicyBundle, actor_template
    from d4pg_tpu.serve.client import PolicyClient
    from d4pg_tpu.serve.server import PolicyServer

    cfg = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(8, 8))
    bundle = PolicyBundle(
        config=cfg,
        actor_params=actor_template(cfg),
        action_low=np.full(2, -1.0, np.float32),
        action_high=np.full(2, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "test"},
        path=None,
    )
    srv = PolicyServer(
        bundle, port=0, max_batch=4, max_wait_us=500, queue_limit=16,
        watch_bundle=False, debug_guards=True,
    )
    srv.start()
    try:
        with PolicyClient("127.0.0.1", srv.port) as c:
            for i in range(5):
                a = c.act(np.full(4, 0.1 * i, np.float32))
                assert a.shape == (2,)
    finally:
        srv.drain()  # runs sentinel.check("serve drain")
    assert srv.ledger.stats()["trips"] == 0
    assert srv.sentinel.count("serve.infer") == len(srv.batcher.buckets)


def test_transfer_guard_clean_on_serve_dispatch():
    """Satellite: serve batcher dispatch runs clean under the transfer
    guard (guard_transfers=True wraps the jitted infer call)."""
    sen = RecompileSentinel().start()
    b = _tiny_batcher(sentinel=sen, guard_transfers=True)
    b.start()
    try:
        futs = [b.submit(np.zeros(3, np.float32)) for _ in range(8)]
        for f in futs:
            assert f.result(timeout=30).shape == (1,)
    finally:
        b.stop()
        sen.stop()
    sen.check("after serve traffic")
    assert sen.count("serve.infer") == len(b.buckets)
