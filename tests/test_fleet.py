"""Collection-fleet tests: wire codecs, numpy policy parity, ingest
server contracts (admission/shed/stale-gen/torn-frame), and the
content-parity claim — a localhost fleet stream and the in-process
writer path produce byte-identical replay content.

Everything here is in-process and device-free except the numpy-policy
parity test (which compiles a tiny actor on CPU) and the subprocess
JAX-free import assertion. The end-to-end 2-process CLI smoke lives in
``tests/test_fleet_smoke.py`` (scripts/fleet_smoke.sh); the fault soak
in ``scripts/chaos_soak.sh``.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.chaos import ChaosInjector, ChaosPlan
from d4pg_tpu.fleet import wire
from d4pg_tpu.fleet.actor import FleetLink, _Spool
from d4pg_tpu.fleet.ingest import IngestServer
from d4pg_tpu.fleet.policy import load_numpy_policy
from d4pg_tpu.replay.nstep_writer import NStepWriter
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import ProtocolError

OBS, ACT, NSTEP, GAMMA = 5, 2, 3, 0.99


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


# ------------------------------------------------------------------- wire
def test_wire_hello_roundtrip():
    payload = wire.encode_hello(
        actor_id="a0", env="Pendulum-v1", obs_dim=OBS, action_dim=ACT,
        n_step=NSTEP, gamma=GAMMA, generation=4,
    )
    doc = wire.decode_hello(payload)
    assert (doc["obs_dim"], doc["action_dim"]) == (OBS, ACT)
    assert (doc["n_step"], doc["gamma"], doc["generation"]) == (NSTEP, GAMMA, 4)
    ok = wire.decode_hello_ok(
        wire.encode_hello_ok(generation=7, max_windows=64, max_inflight=8)
    )
    assert ok == {"generation": 7, "max_windows_per_frame": 64, "max_inflight": 8}


def test_wire_hello_malformed():
    with pytest.raises(ProtocolError, match="malformed HELLO"):
        wire.decode_hello(b"not json")
    with pytest.raises(ProtocolError, match="malformed HELLO"):
        wire.decode_hello(b'{"obs_dim": 3}')  # missing required keys
    with pytest.raises(ProtocolError, match="malformed HELLO"):
        # keys present, wrong types: must be ProtocolError (answered with
        # the documented ERROR+close), never a TypeError that kills the
        # reader thread with a bare close
        wire.decode_hello(
            b'{"obs_dim": null, "action_dim": 3, "n_step": 5,'
            b' "gamma": 0.99}'
        )
    with pytest.raises(ProtocolError, match="malformed HELLO_OK"):
        wire.decode_hello_ok(b'{"generation": 1}')
    with pytest.raises(ProtocolError, match="malformed HELLO_OK"):
        wire.decode_hello_ok(
            b'{"generation": 1, "max_windows_per_frame": null,'
            b' "max_inflight": 4}'
        )


def test_wire_windows_roundtrip():
    rng = np.random.default_rng(0)
    n = 11
    cols = {
        "obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "action": rng.standard_normal((n, ACT)).astype(np.float32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "discount": rng.random(n).astype(np.float32),
    }
    payload = wire.encode_windows(3, **cols)
    gen, got = wire.decode_windows(payload, OBS, ACT)
    assert gen == 3
    for k in cols:
        np.testing.assert_array_equal(got[k], cols[k])


def test_wire_windows_size_mismatch():
    payload = wire.encode_windows(
        0,
        np.zeros((2, OBS), np.float32), np.zeros((2, ACT), np.float32),
        np.zeros(2, np.float32), np.zeros((2, OBS), np.float32),
        np.zeros(2, np.float32),
    )
    with pytest.raises(ProtocolError, match="declares"):
        wire.decode_windows(payload[:-4], OBS, ACT)  # truncated
    with pytest.raises(ProtocolError, match="declares"):
        wire.decode_windows(payload, OBS + 1, ACT)  # wrong dims
    with pytest.raises(ProtocolError, match="header"):
        wire.decode_windows(b"\x01", OBS, ACT)
    ok = wire.encode_windows_ok(5, 2)
    assert wire.decode_windows_ok(ok) == (5, 2)
    with pytest.raises(ProtocolError):
        wire.decode_windows_ok(ok + b"x")


def test_bundle_constants_pinned():
    """fleet.policy restates serve.bundle's layout constants (importing
    serve.bundle pulls JAX, which policy.py must never do) — pin them."""
    from d4pg_tpu.fleet import policy as fp
    from d4pg_tpu.serve import bundle as sb

    assert fp.BUNDLE_VERSION == sb.BUNDLE_VERSION
    assert fp.PARAMS_FILE == sb.PARAMS_FILE
    assert fp.META_FILE == sb.META_FILE


# ----------------------------------------------------------- numpy policy
@pytest.fixture(scope="module")
def tiny_bundle(tmp_path_factory):
    from d4pg_tpu.config import D4PGConfig
    from d4pg_tpu.serve.bundle import actor_template, export_bundle

    cfg = D4PGConfig(obs_dim=OBS, action_dim=ACT, hidden_sizes=(8, 8),
                     n_step=NSTEP, gamma=GAMMA)
    params = actor_template(cfg)
    path = str(tmp_path_factory.mktemp("bundle"))
    export_bundle(path, cfg, params, meta={"generation": 3, "env": "e"})
    return cfg, params, path


def test_numpy_policy_parity_with_jitted_actor(tiny_bundle):
    import jax

    from d4pg_tpu.agent import act_deterministic

    cfg, params, path = tiny_bundle
    pol = load_numpy_policy(path)
    assert (pol.obs_dim, pol.action_dim) == (OBS, ACT)
    assert (pol.n_step, pol.gamma, pol.generation) == (NSTEP, GAMMA, 3)
    obs = np.random.default_rng(1).standard_normal((16, OBS)).astype(np.float32)
    want = np.asarray(jax.jit(act_deterministic, static_argnums=0)(
        cfg, params, obs))
    got = pol.act(obs)
    # XLA may reassociate float reductions; exploration noise dwarfs 1e-5
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_numpy_policy_obs_norm(tiny_bundle):
    import json

    cfg, params, path = tiny_bundle
    from d4pg_tpu.serve.bundle import export_bundle

    stats = {
        "count": 10.0,
        "mean": [0.5] * OBS,
        "m2": [40.0] * OBS,  # var 4.0 -> std 2.0
    }
    p2 = path + "_norm"
    export_bundle(p2, cfg, params, obs_norm_state=stats)
    pol = load_numpy_policy(p2)
    plain = load_numpy_policy(path)
    obs = np.full((1, OBS), 1.5, np.float32)
    # (1.5 - 0.5) / 2.0 = 0.5 must be what the layers see
    np.testing.assert_allclose(
        pol.act(obs), plain.act(np.full((1, OBS), 0.5, np.float32)), atol=1e-6
    )
    # torn/malformed meta is a load error, not a garbage policy
    doc = json.load(open(os.path.join(p2, "bundle.json")))
    doc["agent"]["hidden_sizes"] = [16, 16]
    json.dump(doc, open(os.path.join(p2, "bundle.json"), "w"))
    with pytest.raises(ValueError, match="mismatch|leaves"):
        load_numpy_policy(p2)
    doc["agent"]["hidden_sizes"] = [8, 8]
    doc["agent"]["pixel_shape"] = [8, 8, 2]
    json.dump(doc, open(os.path.join(p2, "bundle.json"), "w"))
    with pytest.raises(ValueError, match="pixel"):
        load_numpy_policy(p2)


def test_fleet_modules_are_jax_free():
    """The actor-host contract: importing every fleet module (plus the
    replay writers the actor reuses) must not load the JAX runtime."""
    code = (
        "import sys\n"
        "import d4pg_tpu.fleet.actor, d4pg_tpu.fleet.ingest\n"
        "import d4pg_tpu.fleet.wire, d4pg_tpu.fleet.policy\n"
        "import d4pg_tpu.replay.nstep_writer\n"
        "bad = [m for m in sys.modules if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, bad\n"
        "print('JAXFREE_OK')\n"
    )
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "JAXFREE_OK" in p.stdout


# ------------------------------------------------------------------ spool
def test_spool_bounded_and_generation_prefix():
    sp = _Spool(limit=4)
    for i in range(6):
        sp.generation = 0 if i < 3 else 1
        sp.add(np.zeros(OBS), np.zeros(ACT), float(i), np.zeros(OBS), 0.9)
    assert len(sp) == 4 and sp.dropped == 2  # oldest two dropped
    tag, cols = sp.take_frame(max_rows=8)
    # rows 2 (gen 0) then 3..5 (gen 1): the frame stops at the gen flip
    assert tag == (0, 0, False) and len(cols["reward"]) == 1
    tag, cols = sp.take_frame(max_rows=2)
    assert tag[0] == 1 and len(cols["reward"]) == 2  # capped at max_rows
    tag, cols = sp.take_frame(max_rows=8)
    assert tag[0] == 1 and len(cols["reward"]) == 1
    assert sp.take_frame(8) is None


def test_spool_stats_and_relabel_prefix():
    """A frame's single (gen, stats_gen, relabeled) tag stays honest
    across a mid-spool stats swap or an original→relabeled phase flip."""
    sp = _Spool(limit=16)
    for i in range(2):
        sp.add(np.zeros(OBS), np.zeros(ACT), float(i), np.zeros(OBS), 0.9)
    sp.relabeled = True
    sp.add(np.zeros(OBS), np.zeros(ACT), 2.0, np.zeros(OBS), 0.9)
    sp.relabeled = False
    sp.stats_generation = 3
    sp.add(np.zeros(OBS), np.zeros(ACT), 3.0, np.zeros(OBS), 0.9)
    tag, cols = sp.take_frame(8)
    assert tag == (0, 0, False) and len(cols["reward"]) == 2
    tag, cols = sp.take_frame(8)
    assert tag == (0, 0, True) and len(cols["reward"]) == 1
    tag, cols = sp.take_frame(8)
    assert tag == (0, 3, False) and len(cols["reward"]) == 1


# ----------------------------------------------------------------- ingest
def _start_server(buffer=None, **kw):
    buf = buffer if buffer is not None else ReplayBuffer(256, OBS, ACT)
    srv = IngestServer(
        buf, obs_dim=OBS, action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
        port=0, **kw,
    ).start()
    return srv, buf


def _handshake(srv, generation=0, **over):
    hello = dict(actor_id="t", env="e", obs_dim=OBS, action_dim=ACT,
                 n_step=NSTEP, gamma=GAMMA, generation=generation)
    hello.update(over)
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    s.settimeout(5)
    protocol.write_frame(s, protocol.HELLO, 1, wire.encode_hello(**hello))
    return s, protocol.read_frame(s)


def _frame_cols(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "action": rng.standard_normal((n, ACT)).astype(np.float32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "discount": rng.random(n).astype(np.float32),
    }


def test_ingest_accepts_windows_and_acks():
    srv, buf = _start_server()
    try:
        s, (t, _r, payload) = _handshake(srv)
        assert t == protocol.HELLO_OK
        ok = wire.decode_hello_ok(payload)
        assert ok["max_inflight"] >= 1 and ok["max_windows_per_frame"] >= 1
        cols = _frame_cols(7)
        protocol.write_frame(
            s, protocol.WINDOWS, 2, wire.encode_windows(0, **cols)
        )
        t, r, payload = protocol.read_frame(s)
        assert (t, r) == (protocol.WINDOWS_OK, 2)
        assert wire.decode_windows_ok(payload) == (7, 0)
        assert _wait(lambda: len(buf) == 7)
        np.testing.assert_array_equal(buf.obs[:7], cols["obs"])
        np.testing.assert_array_equal(buf.reward[:7], cols["reward"])
        # healthz over the same connection
        protocol.write_frame(s, protocol.HEALTHZ, 3)
        t, _r, payload = protocol.read_frame(s)
        import json

        assert t == protocol.HEALTHZ_OK
        h = json.loads(payload)
        assert h["windows_ingested"] == 7 and h["connections"] == 1
        s.close()
    finally:
        srv.close()


def test_ingest_answers_healthz_before_handshake():
    """Monitoring probes send a bare HEALTHZ with no HELLO — the same
    probe the serve port answers (docs/fleet.md); it must not count as a
    protocol error."""
    import json

    srv, _buf = _start_server()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.settimeout(5)
        protocol.write_frame(s, protocol.HEALTHZ, 1)
        t, r, payload = protocol.read_frame(s)
        assert (t, r) == (protocol.HEALTHZ_OK, 1)
        assert json.loads(payload)["protocol_errors"] == 0
        # the connection can still HELLO and stream afterwards
        protocol.write_frame(
            s, protocol.HELLO, 2,
            wire.encode_hello(actor_id="probe", env="e", obs_dim=OBS,
                              action_dim=ACT, n_step=NSTEP, gamma=GAMMA,
                              generation=0),
        )
        t, _r, _p = protocol.read_frame(s)
        assert t == protocol.HELLO_OK
        s.close()
        assert srv.counters()["protocol_errors"] == 0
    finally:
        srv.close()


def test_ingest_refuses_mismatched_hello():
    srv, _buf = _start_server()
    try:
        s, (t, _r, payload) = _handshake(srv, obs_dim=OBS + 1)
        assert t == protocol.ERROR and b"obs_dim" in payload
        assert protocol.read_frame(s) is None  # server closed
        s.close()
        s, (t, _r, payload) = _handshake(srv, n_step=NSTEP + 1, gamma=0.5)
        assert t == protocol.ERROR
        assert b"n_step" in payload and b"gamma" in payload
        s.close()
    finally:
        srv.close()


def test_ingest_refuses_wrong_typed_hello():
    """Keys present but wrong-typed ({"obs_dim": null}): the server must
    answer ERROR and close — not die with an uncaught TypeError and a
    bare close — and count it in protocol_errors."""
    srv, _buf = _start_server()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.settimeout(5)
        protocol.write_frame(
            s, protocol.HELLO, 1,
            b'{"actor_id": "t", "env": "e", "obs_dim": null,'
            b' "action_dim": 3, "n_step": 5, "gamma": 0.99}',
        )
        t, _r, payload = protocol.read_frame(s)
        assert t == protocol.ERROR and b"HELLO" in payload
        assert protocol.read_frame(s) is None  # server closed
        s.close()
        assert _wait(lambda: srv.counters()["protocol_errors"] == 1)
    finally:
        srv.close()


def test_ingest_drops_stale_generation():
    srv, buf = _start_server(max_gen_lag=1)
    try:
        srv.set_generation(5)
        s, (t, _r, payload) = _handshake(srv, generation=0)
        assert t == protocol.HELLO_OK
        # a fresh HELLO_OK tells the actor where the learner is
        assert wire.decode_hello_ok(payload)["generation"] == 5
        cols = _frame_cols(6)
        protocol.write_frame(
            s, protocol.WINDOWS, 2, wire.encode_windows(3, **cols)
        )  # gen 3 < 5 - 1: stale
        t, _r, payload = protocol.read_frame(s)
        assert t == protocol.WINDOWS_OK
        assert wire.decode_windows_ok(payload) == (0, 6)
        protocol.write_frame(
            s, protocol.WINDOWS, 3, wire.encode_windows(4, **cols)
        )  # gen 4 == 5 - 1: inside the lag window
        t, _r, payload = protocol.read_frame(s)
        assert wire.decode_windows_ok(payload) == (6, 0)
        assert _wait(lambda: len(buf) == 6)
        c = srv.counters()
        assert c["windows_dropped_stale_gen"] == 6
        assert c["windows_ingested"] == 6
        s.close()
    finally:
        srv.close()


class _GatedBuffer:
    """add_batch blocks until released — pins the ingest writer thread so
    the admission queue can actually fill."""

    def __init__(self):
        self.gate = threading.Event()
        self.rows = 0

    def add_batch(self, t):
        self.gate.wait(10)
        self.rows += len(t.reward)
        return np.arange(len(t.reward))


def test_ingest_queue_full_sheds_explicitly():
    gated = _GatedBuffer()  # pins the writer thread in its first add_batch
    srv, _buf = _start_server(buffer=gated, queue_limit=1)
    try:
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        cols = _frame_cols(4)
        accepted = 0
        shed = False
        # with the writer pinned and a 1-deep queue, a few frames MUST
        # cross the admission limit; exactly when depends on the writer's
        # pop timing, so accept-until-OVERLOADED is the deterministic form
        for req in range(2, 12):
            protocol.write_frame(
                s, protocol.WINDOWS, req, wire.encode_windows(0, **cols)
            )
            t, r, p = protocol.read_frame(s)
            assert r == req
            if t == protocol.OVERLOADED:
                assert p == b"queue_full"
                shed = True
                break
            assert t == protocol.WINDOWS_OK
            assert wire.decode_windows_ok(p) == (4, 0)
            accepted += 1
        assert shed, "queue never filled"
        assert accepted >= 1
        assert srv.counters()["windows_shed"] == 4
        gated.gate.set()
        # every ADMITTED frame still lands in replay; the shed one never does
        assert _wait(lambda: gated.rows == 4 * accepted)
        s.close()
    finally:
        gated.gate.set()
        srv.close()


def test_ingest_malformed_frame_errors_and_survives():
    srv, buf = _start_server()
    try:
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        s.sendall(b"XX" + b"\x00" * 10)  # bad magic
        t, _r, payload = protocol.read_frame(s)
        assert t == protocol.ERROR and b"magic" in payload
        assert protocol.read_frame(s) is None  # server closed the conn
        s.close()
        # declared-size/content mismatch inside a well-framed payload
        s, _ = _handshake(srv)
        protocol.write_frame(s, protocol.WINDOWS, 2, b"\x00" * 9)
        t, _r, payload = protocol.read_frame(s)
        assert t == protocol.ERROR
        s.close()
        assert _wait(lambda: srv.counters()["protocol_errors"] == 2)
        # the server is still alive and accepting
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        s.close()
        assert len(buf) == 0  # nothing malformed ever reached replay
    finally:
        srv.close()


def test_ingest_torn_frame_drops_windows_whole():
    """Disconnect mid-WINDOWS-frame: the partial frame dies inside
    read_frame, its windows never reach the queue or the buffer."""
    srv, buf = _start_server()
    try:
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        payload = wire.encode_windows(0, **_frame_cols(5))
        hdr = protocol.HEADER.pack(
            protocol.MAGIC, protocol.PROTOCOL_VERSION, protocol.WINDOWS,
            2, len(payload),
        )
        s.sendall(hdr + payload[: len(payload) // 2])
        s.close()  # EOF mid-frame
        assert _wait(lambda: srv.counters()["connections"] == 0)
        time.sleep(0.05)  # writer drain window
        assert len(buf) == 0
        assert srv.counters()["windows_ingested"] == 0
    finally:
        srv.close()


def test_ingest_chaos_partition_aborts_midstream():
    plan = ChaosPlan.parse("seed=1;partition@2")
    srv, buf = _start_server(chaos=ChaosInjector(plan))
    try:
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        cols = _frame_cols(3)
        protocol.write_frame(s, protocol.WINDOWS, 2, wire.encode_windows(0, **cols))
        t, _r, p = protocol.read_frame(s)
        assert wire.decode_windows_ok(p) == (3, 0)
        protocol.write_frame(s, protocol.WINDOWS, 3, wire.encode_windows(0, **cols))
        # injected abortive close: reset or EOF, never a WINDOWS_OK
        with pytest.raises((OSError, ProtocolError, ConnectionError)):
            frame = protocol.read_frame(s)
            if frame is None:
                raise ConnectionError("closed")
            assert frame[0] != protocol.WINDOWS_OK
        s.close()
        assert _wait(lambda: len(buf) == 3)  # only the pre-fault frame
        # server survives: a new connection handshakes fine
        s, (t, _r, _p) = _handshake(srv)
        assert t == protocol.HELLO_OK
        s.close()
    finally:
        srv.close()


def test_ingest_close_drains_admitted_frames():
    gated = _GatedBuffer()
    srv, _ = _start_server(buffer=gated, queue_limit=8)
    s, (t, _r, _p) = _handshake(srv)
    assert t == protocol.HELLO_OK
    for i in range(3):
        protocol.write_frame(
            s, protocol.WINDOWS, 2 + i,
            wire.encode_windows(0, **_frame_cols(2, seed=i)),
        )
    for _ in range(3):
        t, _r, p = protocol.read_frame(s)
        assert wire.decode_windows_ok(p) == (2, 0)
    threading.Timer(0.2, gated.gate.set).start()
    srv.close()  # must block until the queue drained into add_batch
    assert gated.rows == 6
    s.close()


# ---------------------------------------------------------- content parity
def _episode_stream(seed, steps):
    """A deterministic (obs, action, reward, next_obs, term, trunc) stream
    with both episode-end flavors, shared by both writer paths."""
    rng = np.random.default_rng(seed)
    obs = rng.standard_normal(OBS).astype(np.float32)
    t_in_ep = 0
    for i in range(steps):
        action = rng.standard_normal(ACT).astype(np.float32)
        reward = float(rng.standard_normal())
        next_obs = rng.standard_normal(OBS).astype(np.float32)
        t_in_ep += 1
        term = t_in_ep == 13 and (i // 13) % 2 == 0
        trunc = t_in_ep == 13 and not term
        yield obs, action, reward, next_obs, term, trunc
        if term or trunc:
            obs = rng.standard_normal(OBS).astype(np.float32)
            t_in_ep = 0
        else:
            obs = next_obs


def test_fleet_and_inprocess_replay_content_identical():
    """The headline parity claim: the same episode stream through (a) the
    in-process NStepWriter -> ReplayBuffer path and (b) the fleet path —
    NStepWriter -> spool -> framed socket -> IngestServer -> ReplayBuffer
    — leaves byte-identical replay content, in order, zero torn rows."""
    buf_local = ReplayBuffer(512, OBS, ACT)
    w_local = NStepWriter(buf_local, NSTEP, GAMMA)

    srv, buf_fleet = _start_server()
    acks = {"accepted": 0, "stale": 0, "shed": 0, "dropped": 0}

    def on_ack(kind, n):
        acks[kind] += n

    try:
        link = FleetLink(
            "127.0.0.1", srv.port,
            dict(actor_id="p", env="e", obs_dim=OBS, action_dim=ACT,
                 n_step=NSTEP, gamma=GAMMA, generation=0),
            on_ack=on_ack,
        )
        spool = _Spool(4096)
        w_fleet = NStepWriter(spool, NSTEP, GAMMA)
        for obs, action, reward, next_obs, term, trunc in _episode_stream(7, 200):
            w_local.add(obs, action, reward, next_obs, term, trunc)
            w_fleet.add(obs, action, reward, next_obs, term, trunc)
        emitted = len(spool)
        assert emitted == len(buf_local) > 0
        while spool.rows:
            assert link.acquire_credit(5)
            gen, cols = spool.take_frame(link.max_windows)
            link.send_windows(gen, cols)
        assert _wait(lambda: link.inflight() == 0)
        link.close()
        assert _wait(lambda: len(buf_fleet) == emitted)
        assert acks == {"accepted": emitted, "stale": 0, "shed": 0, "dropped": 0}
        n = emitted
        np.testing.assert_array_equal(buf_fleet.obs[:n], buf_local.obs[:n])
        np.testing.assert_array_equal(buf_fleet.action[:n], buf_local.action[:n])
        np.testing.assert_array_equal(buf_fleet.reward[:n], buf_local.reward[:n])
        np.testing.assert_array_equal(
            buf_fleet.next_obs[:n], buf_local.next_obs[:n]
        )
        np.testing.assert_array_equal(
            buf_fleet.discount[:n], buf_local.discount[:n]
        )
    finally:
        srv.close()


def test_actor_step_envs_windows_capture_preassignment_obs(tmp_path):
    """Regression: NStepWriter stores obs WITHOUT copying, and
    ``_step_envs`` assigns INTO the same ``self._obs[i]`` row afterwards
    — without the defensive copy every emitted window's obs silently
    read the row's FUTURE value (wrong (s, a) pairs in replay)."""
    from d4pg_tpu.config import D4PGConfig
    from d4pg_tpu.fleet.actor import FleetActor
    from d4pg_tpu.serve.bundle import actor_template, export_bundle

    cfg = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(8, 8),
                     n_step=NSTEP, gamma=GAMMA)
    bundle = str(tmp_path / "b")
    export_bundle(bundle, cfg, actor_template(cfg),
                  meta={"generation": 0, "env": "Pendulum-v1"})
    actor = FleetActor(
        connect="127.0.0.1:1", bundle_dir=bundle, num_envs=1, seed=3,
    )
    try:
        obs0 = actor._obs[0].copy()
        for _ in range(NSTEP + 1):
            actor._step_envs()
        assert len(actor.spool) >= 1
        _gen, cols = actor.spool.take_frame(1)
        # the first window's obs is the episode's FIRST observation, not
        # whatever the mutated row holds now
        np.testing.assert_array_equal(cols["obs"][0], obs0)
        assert not np.array_equal(actor._obs[0], obs0)
    finally:
        for env in actor.envs:
            env.close()


def _pendulum_bundle(tmp_path):
    from d4pg_tpu.config import D4PGConfig
    from d4pg_tpu.serve.bundle import actor_template, export_bundle

    cfg = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(8, 8),
                     n_step=NSTEP, gamma=GAMMA)
    bundle = str(tmp_path / "bundle")
    export_bundle(bundle, cfg, actor_template(cfg),
                  meta={"generation": 0, "env": "Pendulum-v1"})
    return bundle


def test_actor_refuses_zero_envs(tmp_path):
    """--num-envs 0 must be a clear argument error, not an opaque
    np.stack ValueError from an empty reset list."""
    from d4pg_tpu.fleet.actor import FleetActor

    bundle = _pendulum_bundle(tmp_path)
    with pytest.raises(ValueError, match="num-envs"):
        FleetActor(connect="127.0.0.1:1", bundle_dir=bundle, num_envs=0)


def test_actor_collects_while_disconnected_spool_drops_oldest(tmp_path):
    """The documented disconnect contract: collection CONTINUES while the
    server is unreachable — _ensure_link makes one non-blocking paced
    attempt per call instead of sleeping through the whole Backoff budget
    — and the bounded spool drops its oldest windows (counted in
    windows_dropped_spool)."""
    from d4pg_tpu.fleet.actor import FleetActor

    bundle = _pendulum_bundle(tmp_path)
    actor = FleetActor(
        connect="127.0.0.1:1",  # nothing listens: ECONNREFUSED instantly
        bundle_dir=bundle, num_envs=1, seed=5, batch_windows=4,
        spool_limit=8, reconnect_attempts=50, connect_timeout_s=0.2,
    )
    try:
        t0 = time.monotonic()
        for _ in range(64):
            actor._step_envs()
            while len(actor.spool) >= actor.batch_windows:
                if not actor._flush_once():
                    break
        # the old blocking _ensure_link slept minutes of Backoff here
        assert time.monotonic() - t0 < 10.0
        assert len(actor.spool) <= 8
        s = actor.stats()
        assert s["windows_dropped_spool"] > 0
        assert s["env_steps"] == 64
        assert s["windows_sent"] == 0
    finally:
        for env in actor.envs:
            env.close()


def test_actor_reconnect_budget_exhaustion_raises(tmp_path):
    """Once the bounded retry budget is spent the actor fails loudly
    (RuntimeError), never a silent forever-disconnected spin."""
    from d4pg_tpu.fleet.actor import FleetActor

    bundle = _pendulum_bundle(tmp_path)
    actor = FleetActor(
        connect="127.0.0.1:1", bundle_dir=bundle, num_envs=1, seed=5,
        batch_windows=1, reconnect_attempts=0, connect_timeout_s=0.2,
    )
    try:
        for _ in range(NSTEP + 1):  # emit at least one complete window
            actor._step_envs()
        assert len(actor.spool) >= 1
        with pytest.raises(RuntimeError, match="bounded retries"):
            actor._flush_once()
    finally:
        for env in actor.envs:
            env.close()


def test_drain_credit_wait_honors_deadline_when_stopping(tmp_path):
    """Regression: on the drain path _stop is ALWAYS set (SIGTERM is the
    normal trigger), so the credit wait must run to the drain deadline —
    not give up at the first 0.5 s poll and abandon windows a slow-acking
    but live server would still accept."""
    from d4pg_tpu.fleet.actor import FleetActor

    bundle = _pendulum_bundle(tmp_path)
    lsock = socket.create_server(("127.0.0.1", 0))
    port = lsock.getsockname()[1]
    state = {"frames": 0}

    def serve():
        conn, _ = lsock.accept()
        with conn:
            frame = protocol.read_frame(conn)  # HELLO
            protocol.write_frame(
                conn, protocol.HELLO_OK, frame[1],
                wire.encode_hello_ok(
                    generation=0, max_windows=1, max_inflight=1
                ),
            )
            for _ in range(2):
                t, rid, payload = protocol.read_frame(conn)
                assert t == protocol.WINDOWS
                state["frames"] += 1
                if state["frames"] == 1:
                    time.sleep(1.2)  # ack withheld past two credit polls
                protocol.write_frame(
                    conn, protocol.WINDOWS_OK, rid,
                    wire.encode_windows_ok(1),
                )

    threading.Thread(target=serve, name="slow-ack-ingest",
                     daemon=True).start()
    actor = FleetActor(
        connect=f"127.0.0.1:{port}", bundle_dir=bundle, num_envs=1,
        batch_windows=1, connect_timeout_s=5.0,
    )
    try:
        assert actor._ensure_link()
        obs = np.zeros(3, np.float32)
        act = np.zeros(1, np.float32)
        actor.spool.add(obs, act, 0.0, obs, 1.0)
        actor.spool.add(obs, act, 0.0, obs, 1.0)
        actor._stop.set()  # SIGTERM arrived: this IS the drain state
        deadline = time.monotonic() + 5.0
        assert actor._flush_once(deadline=deadline)  # takes the only credit
        # the second flush must WAIT ~1.2 s for the withheld ack's credit
        assert actor._flush_once(deadline=deadline)
        assert len(actor.spool) == 0
        assert _wait(lambda: state["frames"] == 2)
    finally:
        if actor._link is not None:
            actor._link.close()
        for env in actor.envs:
            env.close()
        lsock.close()


def test_mixed_mode_dead_ingest_thread_fails_loudly(tmp_path):
    """--fleet-listen alongside local collection: no pacing loop consults
    the ingest server, so a dead writer/accept thread must surface at the
    _periodic scrape — not shed every actor frame forever in silence."""
    from d4pg_tpu.config import D4PGConfig, TrainConfig
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(TrainConfig(
        env="pendulum", total_steps=2, warmup_steps=8, batch_size=8,
        num_envs=2, eval_interval=1000, checkpoint_interval=1000,
        log_dir=str(tmp_path), fleet_listen=0,
        agent=D4PGConfig(hidden_sizes=(16, 16)),
    ))
    try:
        t._fleet._thread_error = RuntimeError("boom")
        with pytest.raises(RuntimeError, match="ingest thread died"):
            t.train()
    finally:
        t.close()


def test_fleet_stall_heartbeat_warns(tmp_path, capsys):
    """All remote actors dead = the fleet-only pacing loop waits by design
    (the learner outlives actor churn), but it must say so: a stalled
    ingest logs a heartbeat with the live connection count instead of
    starving in silence."""
    from d4pg_tpu.config import D4PGConfig, TrainConfig
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(TrainConfig(
        env="pendulum", total_steps=2, num_envs=0, fleet_listen=0,
        log_dir=str(tmp_path), agent=D4PGConfig(hidden_sizes=(16, 16)),
    ))
    try:
        t._fleet_stall_check()  # records the zero-ingested baseline
        t._fleet_stall_check()  # no progress, but the interval hasn't run
        assert "no windows ingested" not in capsys.readouterr().out
        t._fleet_stall_t -= 31.0
        t._fleet_stall_check()
        out = capsys.readouterr().out
        assert "no windows ingested" in out and "0 live actor" in out
    finally:
        t.close()


def test_fleet_bundle_without_listen_refused(tmp_path):
    """--fleet-bundle publishes at ingest generation bumps; without
    --fleet-listen it would be silently ignored — refused instead."""
    from d4pg_tpu.config import D4PGConfig, TrainConfig
    from d4pg_tpu.runtime.trainer import Trainer

    with pytest.raises(ValueError, match="fleet-bundle"):
        Trainer(TrainConfig(
            env="pendulum", total_steps=4, num_envs=2,
            fleet_bundle=str(tmp_path / "bundle"), log_dir=str(tmp_path),
            agent=D4PGConfig(hidden_sizes=(16, 16)),
        ))


def test_fleet_only_refuses_async_collect(tmp_path):
    """--async-collect with --num-envs 0 would deadlock the steady-state
    pacing loop (no collector thread exists) — refused at construction."""
    from d4pg_tpu.config import D4PGConfig, TrainConfig
    from d4pg_tpu.runtime.trainer import Trainer

    with pytest.raises(ValueError, match="async-collect"):
        Trainer(TrainConfig(
            env="pendulum", total_steps=4, num_envs=0, fleet_listen=0,
            async_collect=True, log_dir=str(tmp_path),
            agent=D4PGConfig(hidden_sizes=(16, 16)),
        ))


def test_fleet_generation_survives_resume(tmp_path):
    """Regression: the published-bundle generation persists in
    trainer_meta.json and restores on --resume — restarting at 0 would
    regress below generations connected actors already hold, disarming
    the stale-window drop at ingest until the counter caught back up."""
    from d4pg_tpu.config import D4PGConfig, TrainConfig
    from d4pg_tpu.runtime.trainer import Trainer

    def cfg(**kw):
        return TrainConfig(
            env="pendulum", total_steps=4, warmup_steps=32, batch_size=16,
            num_envs=2, eval_interval=1000, checkpoint_interval=4,
            log_dir=str(tmp_path), fleet_listen=0,
            fleet_bundle=str(tmp_path / "bundle"), fleet_publish_interval=2,
            agent=D4PGConfig(hidden_sizes=(16, 16)),
            **kw,
        )

    t = Trainer(cfg())
    try:
        t.train()  # publish interval 2 -> generation bumped past 0
        gen = t._fleet_gen
        assert gen >= 1
    finally:
        t.close()
    r = Trainer(cfg(resume=True))
    try:
        assert r._fleet_gen == gen
        assert r._fleet.generation == gen  # pushed into ingest at publish
    finally:
        r.close()


# -------------------------------------------------------------- fleet link
def test_link_death_sweeps_pending_as_dropped():
    """Unacked frames at disconnect are counted dropped exactly once and
    never resent — the at-most-once reconnect contract. A hand-rolled
    server handshakes, reads one WINDOWS frame, and never acks it."""
    lsock = socket.create_server(("127.0.0.1", 0))
    port = lsock.getsockname()[1]
    state = {}

    def serve():
        conn, _ = lsock.accept()
        state["conn"] = conn
        frame = protocol.read_frame(conn)  # HELLO
        protocol.write_frame(
            conn, protocol.HELLO_OK, frame[1],
            wire.encode_hello_ok(generation=0, max_windows=64, max_inflight=4),
        )
        protocol.read_frame(conn)  # the WINDOWS frame — swallowed, no ack
        state["got"] = True

    threading.Thread(target=serve, name="fake-ingest", daemon=True).start()
    acks = {"accepted": 0, "stale": 0, "shed": 0, "dropped": 0}
    lock = threading.Lock()

    def on_ack(kind, n):
        with lock:
            acks[kind] += n

    link = FleetLink(
        "127.0.0.1", port,
        dict(actor_id="d", env="e", obs_dim=OBS, action_dim=ACT,
             n_step=NSTEP, gamma=GAMMA, generation=0),
        on_ack=on_ack,
    )
    try:
        assert link.acquire_credit(5)
        link.send_windows((0, 0, False), _frame_cols(3))
        assert link.inflight() == 1
        assert _wait(lambda: state.get("got"))
        state["conn"].close()  # server dies with the frame unacked
        assert _wait(lambda: link.dead is not None)
        with lock:
            assert acks == {"accepted": 0, "stale": 0, "shed": 0,
                            "dropped": 3}, acks
        assert link.inflight() == 0  # swept exactly once
    finally:
        link.close()
        lsock.close()


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
