"""Tier-1-safe multihost microbench surface.

The slow half of the ISSUE-17 acceptance (two real 4-device child
processes per topology) lives in ``tests/test_multihost.py``; what runs
every fast pass here is the ingest-scaling half of
``benchmarks/multihost_microbench.run_microbench`` (host-CPU socket
work, ``skip_exact=True``), the committed artifact's schema/attestation
pin, and the refusal behavior of
``tools.d4pglint.schema_check.check_multihost_microbench`` — the gate
that keeps a broken bit-exactness attestation, a nonzero per-grad-step
transfer row, or non-scaling ingest out of the tree.
"""

import copy
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from multihost_microbench import run_microbench  # noqa: E402
from tools.d4pglint.schema_check import check_multihost_microbench  # noqa: E402

ARTIFACT = os.path.join(
    os.path.dirname(__file__), "..", "benchmarks", "multihost_microbench.json"
)


def test_microbench_ingest_half_runs_and_records(tmp_path):
    out_path = str(tmp_path / "multihost_microbench.json")
    out = run_microbench(
        out_path, skip_exact=True, frame_windows=16, duration_s=0.3
    )
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "multihost_microbench"
    sc = out["ingest_scaling"]
    assert sc["writers"] == 2
    assert sc["writers_1_windows_per_sec"] > 0
    # disjoint stacks: the aggregate is exactly the per-writer sum
    assert sc["writers_2_aggregate_windows_per_sec"] == sum(
        sc["per_writer_windows_per_sec"]
    )
    assert "isolated-stack-sum" in sc["methodology"]
    # skip_exact leaves the exactness attestation out entirely — it may
    # only ever be written by the real two-topology run
    assert "bit_exact" not in on_disk


def test_committed_artifact_attests_the_issue_claims():
    assert check_multihost_microbench(ARTIFACT) == []
    with open(ARTIFACT) as f:
        doc = json.load(f)
    be = doc["bit_exact"]
    for key in ("train_state", "adam_moments", "ring", "per_tree",
                "det_pmean", "fold_in_draws"):
        assert be[key] is True
    assert be["mismatches"] == []
    assert be["dispatches"] >= 2
    assert be["state_leaves"] >= 1
    assert be["keys_compared"] > be["state_leaves"]  # ring/PER/draws too
    assert doc["transfer_bytes_per_grad_step"]["procs_1"] == 0
    assert doc["transfer_bytes_per_grad_step"]["procs_2"] == 0
    # the headline scale-out claim: >= 1.8x aggregate with 2 writers
    assert doc["ingest_scaling"]["scaling_x"] >= 1.8


def test_schema_check_refuses_broken_attestations(tmp_path):
    with open(ARTIFACT) as f:
        good = json.load(f)

    def errs_for(mutate):
        doc = copy.deepcopy(good)
        mutate(doc)
        p = str(tmp_path / "doc.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        return check_multihost_microbench(p)

    def set_broken_exactness(d):
        d["bit_exact"]["adam_moments"] = False

    def set_mismatches(d):
        d["bit_exact"]["mismatches"] = ["state_0"]

    def set_transfer_bytes(d):
        d["transfer_bytes_per_grad_step"]["procs_2"] = 4096

    def set_flat_scaling(d):
        d["ingest_scaling"]["scaling_x"] = 0.97

    def set_hand_edited_headline(d):
        d["ingest_scaling"]["scaling_x"] = 7.0  # != aggregate/single

    for mutate in (set_broken_exactness, set_mismatches, set_transfer_bytes,
                   set_flat_scaling, set_hand_edited_headline):
        assert errs_for(mutate), mutate.__name__
    assert errs_for(lambda d: None) == []  # round-trips clean unmutated
