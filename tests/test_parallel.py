"""Multi-device tests on the virtual 8-CPU-device mesh (conftest sets XLA_FLAGS)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.agent import D4PGConfig, create_train_state, jit_train_step
from d4pg_tpu.parallel import (
    auto_parallel_train_step,
    make_dp_train_step,
    make_mesh,
    match_partition_rules,
    shard_batch,
    shard_train_state,
)
from d4pg_tpu.parallel.dp import replicate
from jax.sharding import PartitionSpec as P


def _batch(rng, B=64, obs_dim=3, act_dim=1):
    return {
        "obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, size=(B, act_dim)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, size=B), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "discount": jnp.full((B,), 0.99, jnp.float32),
        "weights": jnp.ones((B,), jnp.float32),
    }


def test_eight_virtual_devices_present():
    assert jax.device_count() == 8


@pytest.mark.slow
def test_dp_train_step_matches_single_device():
    """Sharded-DP and single-device training must agree numerically: the psum
    of shard-mean gradients equals the full-batch mean gradient."""
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(32, 32))
    key = jax.random.PRNGKey(0)
    state_single = create_train_state(config, key)
    state_dp = create_train_state(config, key)

    mesh = make_mesh(dp=8, tp=1)
    dp_step = make_dp_train_step(config, mesh, donate=False)
    single_step = jit_train_step(config, donate=False)

    state_dp = replicate(state_dp, mesh)
    rng = np.random.default_rng(0)
    for i in range(3):
        batch = _batch(rng)
        state_single, m1, p1 = single_step(state_single, batch)
        state_dp, m2, p2 = dp_step(state_dp, batch)
        assert float(m1["critic_loss"]) == pytest.approx(
            float(m2["critic_loss"]), rel=1e-4
        )
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-4, atol=1e-6)
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        state_single.critic_params,
        jax.device_get(state_dp.critic_params),
    )
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-4


def test_dp_batch_not_divisible_raises():
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16))
    mesh = make_mesh(dp=8, tp=1)
    step = make_dp_train_step(config, mesh, donate=False)
    state = replicate(create_train_state(config, jax.random.PRNGKey(0)), mesh)
    with pytest.raises(Exception):
        step(state, _batch(np.random.default_rng(0), B=12))  # 12 % 8 != 0


@pytest.mark.slow
def test_auto_parallel_dp_tp_mesh():
    """GSPMD path on a 4x2 dp×tp mesh: state shards over tp, batch over dp,
    and the step still computes the same loss as single-device."""
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(64, 64))
    key = jax.random.PRNGKey(1)
    mesh = make_mesh(dp=4, tp=2)
    state = create_train_state(config, key)
    state_ref = create_train_state(config, key)

    sharded = shard_train_state(state, mesh)
    step = auto_parallel_train_step(config, mesh, donate=False)
    single = jit_train_step(config, donate=False)

    rng = np.random.default_rng(1)
    batch = _batch(rng)
    out_state, metrics, priorities = step(sharded, shard_batch(batch, mesh))
    _, m_ref, p_ref = single(state_ref, batch)
    assert float(metrics["critic_loss"]) == pytest.approx(
        float(m_ref["critic_loss"]), rel=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(priorities), np.asarray(p_ref), rtol=1e-3, atol=1e-5
    )
    # hidden_0 kernel is column-sharded over tp
    shard_shapes = [
        s.data.shape for s in out_state.critic_params["params"]["hidden_0"]["kernel"].addressable_shards
    ]
    assert all(s[-1] == 32 for s in shard_shapes)  # 64 cols / tp=2


def test_match_partition_rules():
    tree = {
        "params": {
            "hidden_0": {"kernel": np.zeros((4, 8)), "bias": np.zeros(8)},
            "out": {"kernel": np.zeros((8, 2)), "bias": np.zeros(2)},
        }
    }
    from d4pg_tpu.parallel import DEFAULT_RULES

    specs = match_partition_rules(DEFAULT_RULES, tree)
    assert specs["params"]["hidden_0"]["kernel"] == P(None, "tp")
    assert specs["params"]["out"]["kernel"] == P("tp", None)
    assert specs["params"]["out"]["bias"] == P()


def test_match_partition_rules_stacked_twin_axis():
    """Twin-critic stacked leaves ([2, in, out] kernels, [2, out] biases):
    the stack axis must replicate and the rule apply to the TRAILING dims —
    positional application would shard the wrong dimensions silently."""
    tree = {
        "params": {
            "hidden_0": {"kernel": np.zeros((2, 4, 8)), "bias": np.zeros((2, 8))},
            "out": {"kernel": np.zeros((2, 8, 2)), "bias": np.zeros((2, 2))},
        }
    }
    from d4pg_tpu.parallel import DEFAULT_RULES

    specs = match_partition_rules(DEFAULT_RULES, tree)
    assert specs["params"]["hidden_0"]["kernel"] == P(None, None, "tp")
    assert specs["params"]["hidden_0"]["bias"] == P(None, "tp")
    assert specs["params"]["out"]["kernel"] == P(None, "tp", None)


def test_match_partition_rules_gates_undeclared_leading_dims():
    """The stacked-axis prepend fires ONLY for DECLARED stack sizes (the
    twin pair by default): a rank-3 leaf with an undeclared leading size
    matching a dense-written rule must fall back to replication, not
    silently gain a replicated leading axis (ADVICE round-3; the gate is
    now rule-data — DEFAULT_STACK_AXES — not a hardcoded ==2)."""
    tree = {
        "params": {
            # conv-like [width=5, in, out] leaf under a name a dense rule
            # matches: not a declared stack
            "hidden_0": {"kernel": np.zeros((5, 4, 8))},
        }
    }
    from d4pg_tpu.parallel import DEFAULT_RULES

    specs = match_partition_rules(DEFAULT_RULES, tree)
    assert specs["params"]["hidden_0"]["kernel"] == P()


def test_match_partition_rules_declared_ensemble_stack():
    """An E≠2 ensemble stack declared via stack_axes gets the stacked
    treatment the twin pair gets — the satellite fix: before, E=4 leaves
    silently fell to full replication because the gate was ==2."""
    from d4pg_tpu.parallel import DEFAULT_RULES

    tree = {
        "params": {
            "hidden_0": {"kernel": np.zeros((4, 8, 16)), "bias": np.zeros((4, 16))},
            "out": {"kernel": np.zeros((4, 16, 2))},
        }
    }
    specs = match_partition_rules(
        DEFAULT_RULES, tree, stack_axes=((2, None), (4, None))
    )
    assert specs["params"]["hidden_0"]["kernel"] == P(None, None, "tp")
    assert specs["params"]["hidden_0"]["bias"] == P(None, "tp")
    assert specs["params"]["out"]["kernel"] == P(None, "tp", None)
    # undeclared (default stack_axes): the E=4 stack replicates — the old
    # silent behavior, now an explicit declaration decision
    specs_default = match_partition_rules(DEFAULT_RULES, tree)
    assert specs_default["params"]["hidden_0"]["kernel"] == P()


def test_match_partition_rules_mesh_sharded_stack_axis():
    """A stack declared over a mesh axis becomes the member-parallel
    layout: the stack axis shards, trailing uses of the SAME axis drop
    (each member stays whole on its devices; a NamedSharding may name an
    axis once)."""
    from d4pg_tpu.parallel import DEFAULT_RULES

    tree = {
        "params": {
            "hidden_0": {"kernel": np.zeros((4, 8, 16)), "bias": np.zeros((4, 16))},
            "hidden_1": {"kernel": np.zeros((4, 16, 16))},
        }
    }
    specs = match_partition_rules(
        DEFAULT_RULES, tree, stack_axes=((2, None), (4, "tp"))
    )
    assert specs["params"]["hidden_0"]["kernel"] == P("tp", None, None)
    assert specs["params"]["hidden_0"]["bias"] == P("tp", None)
    assert specs["params"]["hidden_1"]["kernel"] == P("tp", None, None)


def test_stack_axes_for_config():
    from d4pg_tpu.agent import D4PGConfig
    from d4pg_tpu.parallel import DEFAULT_STACK_AXES, stack_axes_for

    assert stack_axes_for(D4PGConfig()) == DEFAULT_STACK_AXES
    assert stack_axes_for(D4PGConfig(critic_ensemble=8)) == (
        (2, None), (8, None),
    )
    assert stack_axes_for(D4PGConfig(critic_ensemble=8), "tp") == (
        (2, None), (8, "tp"),
    )


def test_make_shard_and_gather_fns_roundtrip():
    """The EasyLM-shape port: shard_fns place leaves under their rule's
    NamedSharding; gather_fns fetch them WHOLE to host numpy; the
    roundtrip is lossless. This pair is the sharded trainer's checkpoint
    contract (gather on save, re-shard on --resume)."""
    from d4pg_tpu.parallel import DEFAULT_RULES, make_shard_and_gather_fns
    from d4pg_tpu.parallel.partition import _state_specs
    from d4pg_tpu.agent import D4PGConfig, create_train_state

    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(64, 64))
    mesh = make_mesh(dp=4, tp=2)
    state = create_train_state(config, jax.random.PRNGKey(0))
    specs = _state_specs(
        jax.eval_shape(lambda s: s, state), DEFAULT_RULES, mesh
    )
    shard_fns, gather_fns = make_shard_and_gather_fns(specs, mesh)
    from d4pg_tpu.parallel import apply_fns

    sharded = apply_fns(shard_fns, state)
    k = sharded.critic_params["params"]["hidden_0"]["kernel"]
    assert {s.data.shape for s in k.addressable_shards} == {(3, 32)}
    gathered = apply_fns(gather_fns, sharded)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state)),
        jax.tree_util.tree_leaves(gathered),
    ):
        assert isinstance(b, np.ndarray)
        np.testing.assert_array_equal(np.asarray(a), b)


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_auto_parallel_twin_critic_tp():
    """GSPMD dp×tp with twin critics: trains, stays finite, and the stacked
    kernels shard their fan-out (not the twin axis) over tp."""
    config = D4PGConfig(
        obs_dim=3, action_dim=1, hidden_sizes=(64, 64), twin_critic=True
    )
    mesh = make_mesh(dp=4, tp=2)
    state = shard_train_state(create_train_state(config, jax.random.PRNGKey(2)), mesh)
    step = auto_parallel_train_step(config, mesh, donate=False)
    rng = np.random.default_rng(2)
    batch = _batch(rng)
    out_state, metrics, priorities = step(state, shard_batch(batch, mesh))
    assert np.isfinite(float(metrics["critic_loss"]))
    assert priorities.shape == (batch["obs"].shape[0],)
    shard_shapes = [
        s.data.shape
        for s in out_state.critic_params["params"]["hidden_0"][
            "kernel"
        ].addressable_shards
    ]
    # [2, in, 64] kernel: twin axis intact, 64 cols split over tp=2
    assert all(s[0] == 2 and s[-1] == 32 for s in shard_shapes)


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh(dp=16, tp=1)  # only 8 devices
    mesh = make_mesh(tp=2)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


@pytest.mark.slow
def test_dp_fused_scan_matches_sequential_steps():
    """K fused grad steps under DP must equal K sequential DP steps: same
    final params, same per-step priorities."""
    from d4pg_tpu.parallel.dp import make_dp_fused_train_step

    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(32, 32))
    key = jax.random.PRNGKey(1)
    state_seq = create_train_state(config, key)
    state_fused = create_train_state(config, key)

    mesh = make_mesh(dp=8, tp=1)
    seq_step = make_dp_train_step(config, mesh, donate=False)
    fused_step = make_dp_fused_train_step(config, mesh, donate=False)
    state_seq = replicate(state_seq, mesh)
    state_fused = replicate(state_fused, mesh)

    rng = np.random.default_rng(3)
    K = 4
    batches = [_batch(rng) for _ in range(K)]
    pris = []
    for b in batches:
        state_seq, _, p = seq_step(state_seq, b)
        pris.append(np.asarray(p))
    stacked = {k: jnp.stack([b[k] for b in batches]) for k in batches[0]}
    state_fused, metrics_k, pri_k = fused_step(state_fused, stacked)

    assert np.asarray(metrics_k["critic_loss"]).shape == (K,)
    np.testing.assert_allclose(
        np.asarray(pri_k), np.stack(pris), rtol=1e-4, atol=1e-6
    )
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        jax.device_get(state_seq.critic_params),
        jax.device_get(state_fused.critic_params),
    )
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-5


def test_dp_accepts_uniform_batch_without_weights():
    """The DP specs are pytree-PREFIX specs: a uniform-replay batch (no
    PER 'weights' key) must shard and train the same as a PER batch —
    the old hardcoded six-key spec dict made 'weights' load-bearing."""
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16))
    mesh = make_mesh(dp=8, tp=1)
    step = make_dp_train_step(config, mesh, donate=False)
    state = replicate(create_train_state(config, jax.random.PRNGKey(0)), mesh)
    batch = _batch(np.random.default_rng(0))
    del batch["weights"]
    _, metrics, priorities = step(state, batch)
    assert np.isfinite(float(metrics["critic_loss"]))
    assert priorities.shape == (64,)


@pytest.mark.slow
def test_hogwild_dp_identical_shards_reduces_to_single_device():
    """--dp-hogwild exactness anchor: when every replica sees the SAME
    rows, local steps are identical, the closing param pmean averages
    equal values, and the result must match the single-device fused scan
    on one shard bit-nearly."""
    from d4pg_tpu.agent.d4pg import fused_train_scan
    from d4pg_tpu.parallel.dp import make_hogwild_dp_train_step
    from functools import partial

    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(32, 32))
    key = jax.random.PRNGKey(5)
    state_hog = replicate(create_train_state(config, key), make_mesh(dp=8, tp=1))
    state_single = create_train_state(config, key)

    mesh = make_mesh(dp=8, tp=1)
    hog_step = make_hogwild_dp_train_step(config, mesh, donate=False)
    single_fused = jax.jit(partial(fused_train_scan, config))

    rng = np.random.default_rng(7)
    shard = _batch(rng, B=8)  # one replica's rows
    K = 2
    tiled = {  # [K, 64]: all 8 dp shards identical per scan step
        k: jnp.concatenate([v[None]] * K)[:, np.tile(np.arange(8), 8)]
        for k, v in shard.items()
    }
    single_batches = {k: jnp.concatenate([v[None]] * K) for k, v in shard.items()}

    state_hog, m_hog, p_hog = hog_step(state_hog, tiled)
    state_single, m_single, p_single = single_fused(state_single, single_batches)

    np.testing.assert_allclose(
        np.asarray(m_hog["critic_loss"]), np.asarray(m_single["critic_loss"]),
        rtol=1e-5,
    )
    # every replica's priorities = the single-device ones, tiled
    np.testing.assert_allclose(
        np.asarray(p_hog)[:, :8], np.asarray(p_single), rtol=1e-4, atol=1e-6
    )
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        jax.device_get(state_hog.critic_params),
        jax.device_get(state_single.critic_params),
    )
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-5


@pytest.mark.slow
def test_hogwild_dp_staleness_diverges_then_resyncs():
    """With DIFFERENT shards, hogwild params (a) end fully replicated
    across devices (the closing pmean), (b) stay finite, and (c) differ
    from sync-DP on the same data — the staleness is real, not a no-op."""
    from d4pg_tpu.parallel.dp import (
        make_dp_fused_train_step,
        make_hogwild_dp_train_step,
    )

    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(32, 32))
    key = jax.random.PRNGKey(6)
    mesh = make_mesh(dp=8, tp=1)
    state_hog = replicate(create_train_state(config, key), mesh)
    state_sync = replicate(create_train_state(config, key), mesh)
    hog_step = make_hogwild_dp_train_step(config, mesh, donate=False)
    sync_step = make_dp_fused_train_step(config, mesh, donate=False)

    rng = np.random.default_rng(9)
    K = 4
    batches = {k: jnp.stack([_batch(rng)[k] for _ in range(K)])
               for k in _batch(rng)}
    state_hog, m_hog, p_hog = hog_step(state_hog, batches)
    state_sync, _, _ = sync_step(state_sync, batches)

    assert np.isfinite(np.asarray(m_hog["critic_loss"])).all()
    assert p_hog.shape == (K, 64)
    leaf = jax.tree_util.tree_leaves(state_hog.critic_params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:  # resynced: bit-identical on every device
        np.testing.assert_array_equal(shards[0], s)
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        jax.device_get(state_hog.critic_params),
        jax.device_get(state_sync.critic_params),
    )
    assert max(jax.tree_util.tree_leaves(diff)) > 0.0  # staleness is real


def test_initialize_distributed_single_host():
    """Single-host no-op path returns the process/device summary."""
    from d4pg_tpu.parallel.distributed import initialize_distributed

    info = initialize_distributed()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_device_count"] == 8
