"""Tier-1-safe CPU microbench smoke: one fused vs one unfused step.

Keeps the fused-kernel perf surface exercised every test pass even with
the TPU tunnel down — the committed artifact lives at
``benchmarks/cpu_microbench.json`` (regenerate with
``JAX_PLATFORMS=cpu python benchmarks/fused_microbench.py``)."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from fused_microbench import run_microbench  # noqa: E402


def test_microbench_runs_and_records(tmp_path):
    out_path = str(tmp_path / "cpu_microbench.json")
    out = run_microbench(out_path, batch=32, hidden=32, atoms=21, timed_steps=1)
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "fused_vs_unfused_cpu_microbench"
    # both variants timed, both finite
    assert out["unfused_step_ms"] > 0 and np.isfinite(out["unfused_step_ms"])
    assert out["fused_step_ms"] > 0 and np.isfinite(out["fused_step_ms"])
    assert out["fused_over_unfused_time"] > 0
    # bytes proxy present whenever this backend exposes cost analysis
    if "unfused_bytes_accessed" in out:
        assert out["unfused_bytes_accessed"] > 0


def test_committed_artifact_is_current_schema():
    """The committed artifact must stay parseable and carry the regression
    keys (a schema drift here would silently blind the perf guard)."""
    path = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks", "cpu_microbench.json"
    )
    with open(path) as f:
        art = json.load(f)
    assert art["metric"] == "fused_vs_unfused_cpu_microbench"
    for key in ("unfused_step_ms", "fused_step_ms", "fused_over_unfused_time"):
        assert key in art
