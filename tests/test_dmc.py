"""dm_control adapter: state + pixel modes behind the host-env interface.

EGL capability gating: state-mode tests run everywhere (the adapter falls
back to ``MUJOCO_GL=disabled`` on images without libEGL — physics needs no
GL), while rendering tests carry ``@pytest.mark.egl`` — skipped by the
conftest hook when :func:`tests.conftest.has_working_egl`'s cached
subprocess probe (create an EGL context, render a frame) fails. On images
with working EGL the tests run exactly as before — the gate skips, it
never weakens.
"""

import numpy as np
import pytest

pytest.importorskip("dm_control")


def _clean_cpu_env():
    """conftest.clean_cpu_env with the dmc extras: repo-pinned PYTHONPATH
    (the children run train.py from the repo root) and an EGL default."""
    from conftest import clean_cpu_env

    env = clean_cpu_env(pythonpath_repo=True)
    env.setdefault("MUJOCO_GL", "egl")
    return env

@pytest.fixture(scope="module")
def state_env():
    from d4pg_tpu.envs import make_env

    return make_env("dmc:cartpole:swingup", 200)


def test_state_mode_shapes_and_protocol(state_env):
    env = state_env
    assert env.action_dim == 1
    assert env.observation_dim == 5  # cartpole: position(3) + velocity(2)
    obs = env.reset(seed=0)
    assert obs.shape == (5,) and obs.dtype == np.float32
    obs2, r, term, trunc, info = env.step(np.array([0.5], np.float32))
    assert obs2.shape == (5,)
    assert 0.0 <= r <= 1.0  # suite rewards are [0, 1] per step
    assert term is False  # suite tasks truncate, never terminate


def test_state_mode_truncates_at_limit(state_env):
    env = state_env
    env.reset(seed=1)
    trunc = False
    for _ in range(200):
        _, _, _, trunc, _ = env.step(np.array([0.0], np.float32))
        if trunc:
            break
    assert trunc


def test_action_repeat_sums_rewards_and_divides_horizon():
    """Repeat semantics (DrQ convention): one agent step == N control steps
    with the SAME action and the rewards summed; the agent-step horizon is
    the native horizon divided by N, so episode returns keep their
    [0, horizon] scale."""
    from d4pg_tpu.envs import make_env

    single = make_env("dmc:cartpole:swingup")
    repeat = make_env("dmc:cartpole:swingup", action_repeat=4)
    assert single.max_episode_steps == 1000  # suite native horizon
    assert repeat.max_episode_steps == 250

    single.reset(seed=7)
    repeat.reset(seed=7)
    actions = [np.array([a], np.float32) for a in (0.3, -0.8, 1.0)]
    for a in actions:
        r_sum = 0.0
        for _ in range(4):
            o1, r, _, _, _ = single.step(a)
            r_sum += r
        o4, r4, term, trunc, _ = repeat.step(a)
        # identical physics trajectory → identical summed reward and obs
        np.testing.assert_allclose(o4, o1, rtol=1e-6, atol=1e-6)
        assert abs(r4 - r_sum) < 1e-9
        assert not term and not trunc


def test_action_repeat_rejected_for_non_dmc():
    from d4pg_tpu.envs import make_env

    with pytest.raises(ValueError, match="action-repeat"):
        make_env("pendulum", action_repeat=2)
    from d4pg_tpu.envs.gym_adapter import make_host_env

    with pytest.raises(ValueError, match="action-repeat"):
        make_host_env("Pendulum-v1", action_repeat=2)


def test_pixel_mode_without_gl_raises_clearly():
    """With GL unavailable (MUJOCO_GL=disabled — what the adapter's probe
    picks on an image without libEGL) pixel mode must fail with an
    actionable message at construction, not an AttributeError deep inside
    PyOpenGL; state mode in the same process keeps working."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(
        """
        import os
        os.environ["MUJOCO_GL"] = "disabled"
        from d4pg_tpu.envs.dmc_adapter import make_dmc
        env = make_dmc("dmc:cartpole:swingup")
        env.reset(seed=0)  # state-mode physics needs no GL
        try:
            make_dmc("dmc_pixels:cartpole:swingup")
        except RuntimeError as e:
            assert "GL backend" in str(e), e
            print("NO_GL_CLEAR_ERROR_OK")
        """
    )
    p = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env=_clean_cpu_env(),
    )
    assert "NO_GL_CLEAR_ERROR_OK" in p.stdout, p.stdout + p.stderr


@pytest.mark.slow
@pytest.mark.egl
def test_pixel_mode_convention():
    """Pixels follow the repo convention: flattened [H, W, 2] floats in
    [0,1], two-frame grayscale stack, pixel_shape advertised for the conv
    encoder + uint8 replay. Subprocess: EGL rendering in the main pytest
    process segfaults at interpreter teardown (torch/h5py/JAX all loaded)."""
    import os
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(
        f"""
        import sys
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
        import numpy as np
        from d4pg_tpu.envs import make_env

        env = make_env("dmc_pixels:cartpole:swingup", 100)
        assert env.pixel_shape == (48, 48, 2)
        assert env.observation_dim == 48 * 48 * 2
        obs = env.reset(seed=0)
        assert obs.shape == (48 * 48 * 2,)
        assert obs.min() >= 0.0 and obs.max() <= 1.0 and obs.max() > 0.05
        frames = obs.reshape(48, 48, 2)
        np.testing.assert_allclose(frames[..., 0], frames[..., 1])
        prev = frames[..., 0]
        obs2, *_ = env.step(np.array([1.0], np.float32))
        frames2 = obs2.reshape(48, 48, 2)
        np.testing.assert_allclose(frames2[..., 1], prev)
        print("DMC_PIXEL_CONV_OK")
        """
    )
    p = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=600, env=_clean_cpu_env(),
    )
    assert "DMC_PIXEL_CONV_OK" in p.stdout, p.stdout + p.stderr


@pytest.mark.slow
@pytest.mark.egl
def test_pixel_mode_trains_with_conv_encoder(tmp_path):
    """Trainer end-to-end on dm_control pixels: _reconcile_config adopts
    pixel_shape from the live env, replay stores uint8, conv encoder runs.

    Runs in a SUBPROCESS: EGL rendering inside the main pytest process
    (with torch/h5py/pandas and the JAX runtime all loaded) segfaults at
    interpreter teardown; a fresh interpreter is clean."""
    import os
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(
        f"""
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import dataclasses
        import numpy as np
        import sys
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
        from train import build_parser, config_from_args
        from d4pg_tpu.runtime.trainer import Trainer

        args = build_parser().parse_args([
            "--env", "dmc_pixels:cartpole:swingup",
            "--total-steps", "3", "--warmup", "40",
            "--eval-interval", "1000000", "--checkpoint-interval", "1000000",
            "--num-envs", "1", "--bsize", "8", "--rmsize", "500",
            "--max-steps", "40",
            "--log-dir", {str(tmp_path / "dmc")!r},
        ])
        cfg = config_from_args(args)
        cfg = dataclasses.replace(
            cfg,
            agent=dataclasses.replace(
                cfg.agent, hidden_sizes=(32, 32), encoder_embed_dim=16
            ),
        )
        t = Trainer(cfg)
        assert t.config.agent.pixel_shape == (48, 48, 2)
        assert t.buffer.obs.dtype == np.uint8
        t.warmup()
        out = t.train()
        t.close()
        assert np.isfinite(out["critic_loss"])
        print("DMC_PIXEL_TRAIN_OK")
        """
    )
    p = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=900,
        env=_clean_cpu_env(),
    )
    assert "DMC_PIXEL_TRAIN_OK" in p.stdout, p.stdout + p.stderr


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
@pytest.mark.egl   # a GL-less image can't even construct the pixel env
def test_pixel_env_refuses_pooled_collection(tmp_path):
    """Concurrent cross-process EGL rendering deadlocks on this image's GL
    stack (module docstring) — the trainer must refuse pooled/async
    collection for pixel dm_control envs instead of hanging silently."""
    from d4pg_tpu.runtime.trainer import Trainer
    from train import build_parser, config_from_args

    args = build_parser().parse_args(
        [
            "--env", "dmc_pixels:cartpole:swingup", "--num-envs", "4",
            "--total-steps", "4", "--bsize", "8",
            "--log-dir", str(tmp_path / "px"),
        ]
    )
    with pytest.raises(ValueError, match="EGL"):
        Trainer(config_from_args(args))
