"""Tier-1 smokes for the C10k front-end microbench.

Three halves, mirroring the other benchmark smokes:

- the GENERATOR runs end-to-end at a small connection count within the
  tier-1 clock budget (the 60 s clock-guard convention). The O(1)-threads
  and accounting-identity claims are asserted even here — they hold at
  ANY scale; only the 10000-connection floor needs the full run;
- the COMMITTED artifact (``benchmarks/c10k_microbench.json``) keeps its
  schema and the acceptance headlines: ≥10000 held connections on one
  router subprocess, interactive p99 inside its SLO beside them, thread
  growth inside a constant budget, identity exact, rc-0 drain.
  Regenerate: ``JAX_PLATFORMS=cpu python benchmarks/c10k_microbench.py``;
- the SCHEMA GATE (``schema_check.check_c10k_microbench``) accepts the
  committed artifact and refuses every mutant a regression would write —
  a regressed artifact must be uncommittable, not merely alarming.
"""

from __future__ import annotations

import copy
import json
import os
import time

import pytest

pytest.importorskip("jax")

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "c10k_microbench.json",
)

# The stated fast-tier budget for this suite's generator leg (the tier-1
# clock guard convention): measured ~8 s on the 2-core CI box; 60 s is
# the hard line past which this belongs behind the slow marker instead.
FAST_BUDGET_S = 60.0


def test_generator_runs_at_small_shape_within_budget(tmp_path):
    from benchmarks.c10k_microbench import run_microbench

    t0 = time.monotonic()
    out_path = str(tmp_path / "c10k_microbench.json")
    out = run_microbench(
        out_path,
        conns=300,
        baseline_conns=50,
        interactive_conns=2,
        duration_s=1.0,
    )
    elapsed = time.monotonic() - t0
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "c10k_microbench"
    # correctness at ANY scale: every connection accepted and held...
    assert out["held_connections"] >= 300
    # ...on O(1) threads (the generator itself asserts this; re-pin the
    # numbers so the contract is visible here too)
    th = out["threads"]
    assert th["growth"] <= th["growth_budget"]
    assert th["threads_at_max"] == th["threads_baseline"] + th["growth"]
    # ...with real answers beside the idle population and nothing lost
    inter = out["interactive"]
    assert inter["ok"] > 0 and inter["error"] == 0
    assert out["identity"]["ok"] is True
    assert out["router_rc"] == 0
    assert elapsed < FAST_BUDGET_S, (
        f"c10k microbench smoke took {elapsed:.1f}s — past the stated "
        f"{FAST_BUDGET_S:.0f}s fast-tier budget; shrink the shape or "
        "move it behind the slow marker"
    )


def test_committed_artifact_meets_acceptance():
    with open(ARTIFACT) as f:
        art = json.load(f)
    assert art["metric"] == "c10k_microbench"
    assert art["backend"] == "cpu"  # chip-independent artifact
    # THE headline: ten thousand concurrent connections on one router
    assert art["held_connections"] >= 10000
    assert art["netio"]["conns_total"] >= art["conns_target"]
    # ...held on O(1) threads (a thread-per-connection front-end shows
    # growth ~= conns here, thousands past any constant budget)
    th = art["threads"]
    assert th["growth"] <= th["growth_budget"] <= 8
    # ...while interactive traffic stays inside its SLO
    inter = art["interactive"]
    assert 0 < inter["p99_ms"] <= art["slo_ms"]
    assert inter["ok"] > 0 and inter["error"] == 0
    # ...and the books are exact at drain
    assert art["identity"]["ok"] is True
    assert art["identity"]["verdicts"], "no flow-verdict was recorded"
    assert art["router_rc"] == 0


def test_schema_check_accepts_committed_and_refuses_mutants(tmp_path):
    from tools.d4pglint.schema_check import check_c10k_microbench

    with open(ARTIFACT) as f:
        art = json.load(f)
    assert check_c10k_microbench(ARTIFACT) == []

    def refused(mutate, needle):
        doc = copy.deepcopy(art)
        mutate(doc)
        p = str(tmp_path / "mutant.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        errs = check_c10k_microbench(p)
        assert errs and any(needle in e for e in errs), (needle, errs)

    refused(lambda d: d["identity"].__setitem__("ok", False),
            "identity.ok")
    refused(lambda d: d["identity"]["verdicts"][0].__setitem__("ok", False),
            "flow-verdict")
    refused(lambda d: d.__setitem__("held_connections", 9999),
            "held_connections")
    refused(lambda d: d["threads"].__setitem__("growth", 5000),
            "threads.growth")
    refused(lambda d: d["threads"].__setitem__("growth_budget", 64),
            "growth_budget")
    refused(lambda d: d["interactive"].__setitem__(
        "p99_ms", art["slo_ms"] + 1.0), "p99_ms")
    refused(lambda d: d["interactive"].__setitem__("error", 3),
            "interactive.error")
    refused(lambda d: d.__setitem__("router_rc", 1), "router_rc")
    refused(lambda d: d.pop("threads"), "threads")
