"""Tier-1 serve smoke: the whole train → --export-bundle → serve →
round-trip → SIGTERM drain path through the real CLIs
(``scripts/serve_smoke.sh``), in a subprocess with a clean CPU backend.

This is THE end-to-end smoke for the serving subsystem (conftest fast-tier
policy): everything else serve-related tests layers in-process; only this
one proves the shipped commands compose.
"""

import os
import subprocess
import sys

from conftest import clean_cpu_env


def test_serve_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["SERVE_SMOKE_DIR"] = str(tmp_path / "run")
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "serve_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=840,
        env=env,
        cwd=repo,
    )
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "SERVE_SMOKE_ROUNDTRIP_OK" in p.stdout, out[-4000:]
    assert "SERVE_SMOKE_OK" in p.stdout, out[-4000:]
    # the exported bundle is a real directory artifact
    assert os.path.exists(str(tmp_path / "run" / "bundle" / "bundle.json"))


if __name__ == "__main__":
    sys.exit(0)
