"""Serving subsystem units: protocol frames, bundles, the dynamic batcher.

Socket-level end-to-end (including the fault paths the server must
survive) lives in test_serve_server.py; this file covers the pieces in
isolation so a failure points at a layer.
"""

import socket
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.agent import act_deterministic
from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.batcher import DynamicBatcher, ShedError, default_buckets
from d4pg_tpu.serve.bundle import (
    actor_template,
    config_from_json,
    config_to_json,
    export_bundle,
    load_bundle,
)
from d4pg_tpu.serve.protocol import ProtocolError


@pytest.fixture(scope="module")
def tiny():
    cfg = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(8, 8))
    return cfg, actor_template(cfg)


# ---------------------------------------------------------------- protocol
def _sock_pair():
    a, b = socket.socketpair()
    return a, b


def test_protocol_roundtrip():
    a, b = _sock_pair()
    obs = np.arange(5, dtype=np.float32)
    protocol.write_frame(a, protocol.ACT, 7, protocol.encode_act(obs, 1234))
    msg_type, req_id, payload = protocol.read_frame(b)
    assert (msg_type, req_id) == (protocol.ACT, 7)
    got, deadline = protocol.decode_act(payload, 5)
    np.testing.assert_array_equal(got, obs)
    assert deadline == 1234
    act = np.array([0.5, -0.5], np.float32)
    protocol.write_frame(b, protocol.ACT_OK, 7, protocol.encode_action(act))
    _, _, pl = protocol.read_frame(a)
    np.testing.assert_array_equal(protocol.decode_action(pl), act)
    a.close(), b.close()


def test_protocol_clean_eof_and_mid_frame_eof():
    a, b = _sock_pair()
    a.close()
    assert protocol.read_frame(b) is None  # clean EOF between frames
    b.close()
    a, b = _sock_pair()
    hdr = protocol.HEADER.pack(protocol.MAGIC, protocol.PROTOCOL_VERSION,
                               protocol.ACT, 1, 64)
    a.sendall(hdr + b"short")
    a.close()
    with pytest.raises(ProtocolError, match="EOF"):
        protocol.read_frame(b)
    b.close()


def test_protocol_rejects_bad_magic_version_and_oversize():
    a, b = _sock_pair()
    a.sendall(b"XX" + bytes(protocol.HEADER.size - 2))
    with pytest.raises(ProtocolError, match="magic"):
        protocol.read_frame(b)
    a2, b2 = _sock_pair()
    a2.sendall(protocol.HEADER.pack(protocol.MAGIC, 99, protocol.ACT, 1, 0))
    with pytest.raises(ProtocolError, match="version"):
        protocol.read_frame(b2)
    a3, b3 = _sock_pair()
    a3.sendall(
        protocol.HEADER.pack(
            protocol.MAGIC, protocol.PROTOCOL_VERSION, protocol.ACT, 1,
            protocol.MAX_PAYLOAD + 1,
        )
    )
    with pytest.raises(ProtocolError, match="max"):
        protocol.read_frame(b3)
    with pytest.raises(ProtocolError):
        protocol.write_frame(a3, protocol.ACT, 1, b"x" * (protocol.MAX_PAYLOAD + 1))
    for s in (a, b, a2, b2, a3, b3):
        s.close()


def test_decode_act_size_mismatch():
    with pytest.raises(ProtocolError, match="expected"):
        protocol.decode_act(b"\x00" * 11, obs_dim=4)


# ------------------------------------------------------------------ bundle
def test_config_json_roundtrip_preserves_tuples():
    cfg = D4PGConfig(
        obs_dim=7, action_dim=3, hidden_sizes=(32, 16), pixel_shape=(8, 8, 2)
    )
    back = config_from_json(config_to_json(cfg))
    assert back == cfg
    assert isinstance(back.hidden_sizes, tuple)
    assert isinstance(back.pixel_shape, tuple)


def test_config_json_unknown_field_is_hard_error():
    d = config_to_json(D4PGConfig())
    d["from_the_future"] = 1
    with pytest.raises(ValueError, match="from_the_future"):
        config_from_json(d)


def test_bundle_roundtrip_and_validation(tmp_path, tiny):
    cfg, params = tiny
    d = str(tmp_path / "b")
    export_bundle(
        d, cfg, params,
        action_low=[-2.0, -1.0], action_high=[2.0, 1.0],
        obs_norm_state={"count": 4.0, "mean": [0.0] * 4, "m2": [1.0] * 4},
        meta={"source": "test"},
    )
    b = load_bundle(d)
    assert b.config == cfg and b.meta["source"] == "test"
    np.testing.assert_array_equal(b.action_low, [-2.0, -1.0])
    for a, bb in zip(
        __import__("jax").tree_util.tree_leaves(params),
        __import__("jax").tree_util.tree_leaves(b.actor_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    # config/params mismatch must fail loudly, not serve garbage
    wide = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(16, 16))
    export_bundle(str(tmp_path / "c"), cfg, params)
    import json
    import os

    meta_path = os.path.join(str(tmp_path / "c"), "bundle.json")
    with open(meta_path) as f:
        doc = json.load(f)
    doc["agent"] = config_to_json(wide)
    with open(meta_path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match="shape"):
        load_bundle(str(tmp_path / "c"))


def test_bundle_rejects_mismatched_obs_norm(tmp_path, tiny):
    cfg, params = tiny
    with pytest.raises(ValueError):
        export_bundle(
            str(tmp_path / "bad"), cfg, params,
            action_low=[1.0, 1.0], action_high=[-1.0, -1.0],
        )
    d = str(tmp_path / "b2")
    export_bundle(
        d, cfg, params, obs_norm_state={"count": 1.0, "mean": [0.0], "m2": [1.0]}
    )
    with pytest.raises(ValueError, match="obs_norm"):
        load_bundle(d)


def test_export_prefers_best_obs_norm_snapshot(tmp_path):
    """--export-bundle pairs best_actor.npz with the normalizer snapshot
    captured when the champion was scored (best_obs_norm.json), NOT the
    continually-drifting trainer_meta.json statistics."""
    import json

    import jax

    from train import build_parser, config_from_args, export_bundle_from_run

    run = tmp_path / "run"
    ckpt = run / "checkpoints"
    ckpt.mkdir(parents=True)
    cfg = config_from_args(
        build_parser().parse_args(
            [
                "--env", "Pendulum-v1", "--obs-norm",
                "--hidden-sizes", "8,8", "--log-dir", str(run),
            ]
        )
    )
    params = actor_template(
        __import__("dataclasses").replace(
            cfg.agent, obs_dim=3, action_dim=1
        )
    )
    leaves = jax.tree_util.tree_leaves(params)
    with open(ckpt / "best_actor.npz", "wb") as f:
        np.savez(
            f, **{f"leaf_{i:04d}": np.asarray(l) for i, l in enumerate(leaves)}
        )
    drifted = {"count": 99.0, "mean": [9.0] * 3, "m2": [9.0] * 3}
    at_best = {"count": 5.0, "mean": [1.0] * 3, "m2": [5.0] * 3}
    with open(ckpt / "trainer_meta.json", "w") as f:
        json.dump({"env_steps": 123, "ewma_return": 0.0, "obs_norm": drifted}, f)
    with open(ckpt / "best_obs_norm.json", "w") as f:
        json.dump(at_best, f)
    out = export_bundle_from_run(cfg, str(tmp_path / "bundle"))
    b = load_bundle(out)
    assert b.obs_norm == at_best  # the paired snapshot, not the drifted meta
    assert b.meta["source"] == "best_actor.npz"


# ----------------------------------------------------------------- batcher
def test_default_buckets_end_at_max_batch():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(12) == (1, 2, 4, 8, 12)
    assert default_buckets(1) == (1,)


def test_batcher_matches_direct_forward_with_norm_and_bounds(tiny):
    cfg, params = tiny
    stats = {"count": 9.0, "mean": [0.5] * 4, "m2": [9.0] * 4}
    b = DynamicBatcher(
        cfg, params, max_batch=4, max_wait_us=200, queue_limit=16,
        action_low=[-3.0, 0.0], action_high=[3.0, 2.0], obs_norm_stats=stats,
    )
    b.start()
    try:
        rng = np.random.default_rng(0)
        obs = rng.normal(size=(6, 4)).astype(np.float32)
        futs = [b.submit(o) for o in obs]
        got = np.stack([f.result(30) for f in futs])
        mean = np.full(4, 0.5, np.float32)
        std = np.maximum(np.sqrt(np.full(4, 1.0)), 1e-2).astype(np.float32)
        normed = np.clip((obs - mean) / std, -5, 5)
        ref = np.clip(np.asarray(act_deterministic(cfg, params, normed)), -1, 1)
        low = np.array([-3.0, 0.0], np.float32)
        high = np.array([3.0, 2.0], np.float32)
        ref = low + (ref + 1.0) * 0.5 * (high - low)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert np.all(got >= low - 1e-6) and np.all(got <= high + 1e-6)
    finally:
        b.stop()


def _slow_batcher(cfg, params, delay_s: float, **kw):
    """Batcher whose device call sleeps — the slow-device stub that makes
    queue buildup deterministic."""
    b = DynamicBatcher(cfg, params, **kw)
    real = b._infer

    def slow(p, o):
        time.sleep(delay_s)
        return real(p, o)

    b._infer = slow
    return b


def test_batcher_queue_full_sheds_synchronously(tiny):
    cfg, params = tiny
    b = _slow_batcher(
        cfg, params, 0.2, max_batch=2, max_wait_us=50_000, queue_limit=2
    )
    b.start()
    try:
        obs = np.zeros(4, np.float32)
        futs = [b.submit(obs) for _ in range(2)]  # consumed into a batch
        time.sleep(0.05)  # device thread now sleeping inside the stub
        futs += [b.submit(obs), b.submit(obs)]  # fills the queue
        with pytest.raises(ShedError, match="queue_full"):
            b.submit(obs)
        assert b.stats.shed_queue_full == 1
        for f in futs:
            assert f.result(30).shape == (2,)  # admitted work still answered
    finally:
        b.stop()


def test_batcher_deadline_expired_requests_are_dropped(tiny):
    cfg, params = tiny
    b = _slow_batcher(
        cfg, params, 0.25, max_batch=2, max_wait_us=0, queue_limit=16
    )
    b.start()
    try:
        obs = np.zeros(4, np.float32)
        first = [b.submit(obs) for _ in range(2)]  # occupy the device
        time.sleep(0.05)
        doomed = b.submit(obs, deadline_s=0.05)  # expires while queued
        ok = b.submit(obs, deadline_s=30.0)
        with pytest.raises(ShedError, match="deadline"):
            doomed.result(30)
        assert ok.result(30).shape == (2,)
        assert b.stats.shed_deadline == 1
        for f in first:
            f.result(30)
    finally:
        b.stop()


def test_batcher_drain_answers_queued_then_sheds_new(tiny):
    cfg, params = tiny
    b = _slow_batcher(
        cfg, params, 0.1, max_batch=2, max_wait_us=0, queue_limit=32
    )
    b.start()
    obs = np.zeros(4, np.float32)
    futs = [b.submit(obs) for _ in range(6)]
    stopper = threading.Thread(target=b.stop, kwargs={"drain": True})
    stopper.start()
    time.sleep(0.02)
    with pytest.raises(ShedError, match="draining|queue_full"):
        for _ in range(40):  # racing the drain flag; one of them must shed
            b.submit(obs)
    stopper.join(timeout=30)
    assert not stopper.is_alive()
    for f in futs:
        assert f.result(5).shape == (2,)  # everything admitted was answered


def test_batcher_hot_swap_no_recompile_and_validates(tiny):
    cfg, params = tiny
    import jax

    b = DynamicBatcher(cfg, params, max_batch=4, max_wait_us=100, queue_limit=16)
    b.start()
    try:
        obs = np.ones(4, np.float32)
        a_old = b.submit(obs).result(30)
        compiles = b.compile_count
        assert compiles == len(b.buckets)  # warmup compiled each bucket once
        bumped = jax.tree_util.tree_map(lambda x: x + 0.25, params)
        b.set_params(bumped)
        a_new = b.submit(obs).result(30)
        assert b.compile_count == compiles  # the whole point of hot reload
        assert not np.allclose(a_old, a_new)  # new params actually serve
        with pytest.raises(ValueError, match="shape"):
            b.set_params(
                actor_template(
                    D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(16, 16))
                )
            )
    finally:
        b.stop()


def test_batcher_pads_to_buckets_and_counts(tiny):
    cfg, params = tiny
    b = _slow_batcher(
        cfg, params, 0.05, max_batch=8, max_wait_us=50_000, queue_limit=64
    )
    b.start()
    try:
        obs = np.zeros(4, np.float32)
        # 3 requests land within one window → bucket 4, one padded row
        futs = [b.submit(obs) for _ in range(3)]
        for f in futs:
            f.result(30)
        hist = b.stats.batch_hist.snapshot()
        # the 3 requests share one 50 ms window → one bucket-4 batch with
        # exactly one padded row
        assert hist["le_4"] >= 1
        assert b.stats.padded_rows_total >= 1
    finally:
        b.stop()
