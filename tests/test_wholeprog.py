"""Whole-program analysis self-tests (tools/d4pglint/wholeprog/).

Per the established convention each analyzer has bad-fires / good-clean /
suppression fixtures in tests/test_d4pglint.py:FIXTURES (single-file);
this file covers what single-file fixtures cannot:

- CROSS-FILE lock-order cycles (the whole point of the whole-program
  pass), the committed ``benchmarks/lock_order_graph.json`` artifact and
  its schema_check pins (shape, acyclicity, freshness);
- the runtime lock-order witness, including the seeded synthetic
  deadlock that the witness catches at run time AND the static pass
  flags in the equivalent source;
- the shape-aware partition-coverage gate: passes on the real model zoo,
  FAILS on the injected undeclared-stack fixture (the PR-9 bug, seeded);
- the docs-catalog drift check and the remaining analyzer sub-rules
  (bounded-queue admission, protocol silent-drop, unused-suppression
  pass B);
- regression tests for the real findings the repo sweep surfaced
  (FleetLink's silently-unaccounted unexpected reply type).
"""

from __future__ import annotations

import ast
import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from tools.d4pglint.core import lint_source, lint_sources
from tools.d4pglint.schema_check import check_lock_order_graph
from tools.d4pglint.wholeprog.docscheck import check_docs
from tools.d4pglint.wholeprog.lockgraph import (
    build_lock_graph,
    find_cycles,
    is_acyclic,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _files(sources: dict) -> dict:
    return {
        rel: (ast.parse(textwrap.dedent(src)), textwrap.dedent(src).splitlines())
        for rel, src in sources.items()
    }


# ------------------------------------------------------- cross-file lock order
_CROSS_A = """
import threading


class Source:
    def __init__(self):
        self._alock = threading.Lock()
        self.sink = Sink()

    def push(self):
        with self._alock:
            self.sink.write()

    def lock_a(self):
        with self._alock:
            pass
"""

_CROSS_B_GOOD = """
import threading


class Sink:
    def __init__(self):
        self._block = threading.Lock()

    def write(self):
        with self._block:
            pass
"""

_CROSS_B_BAD = _CROSS_B_GOOD + """

    def bind(self):
        from d4pg_tpu.runtime.a import Source

        self.owner = Source()

    def reverse(self):
        with self._block:
            self.owner.lock_a()
"""


def test_lock_order_cycle_across_files_fires():
    findings, _ = lint_sources(
        {"d4pg_tpu/runtime/a.py": textwrap.dedent(_CROSS_A),
         "d4pg_tpu/runtime/b.py": textwrap.dedent(_CROSS_B_BAD)},
        checks=["lock-order"],
    )
    assert findings, "cross-file inversion not detected"
    assert all(f.check == "lock-order" for f in findings)
    assert "Source._alock" in findings[0].message
    assert "Sink._block" in findings[0].message


def test_lock_order_cross_file_nesting_without_cycle_is_clean():
    findings, _ = lint_sources(
        {"d4pg_tpu/runtime/a.py": textwrap.dedent(_CROSS_A),
         "d4pg_tpu/runtime/b.py": textwrap.dedent(_CROSS_B_GOOD)},
        checks=["lock-order"],
    )
    assert findings == [], findings
    # ...but the EDGE is in the graph (the nesting was seen, just acyclic)
    graph = build_lock_graph(_files(
        {"d4pg_tpu/runtime/a.py": _CROSS_A,
         "d4pg_tpu/runtime/b.py": _CROSS_B_GOOD}
    ))
    pairs = {(e["from"], e["to"]) for e in graph["edges"]}
    assert ("Source._alock", "Sink._block") in pairs


def test_find_cycles_and_acyclicity_primitives():
    assert find_cycles([("a", "b"), ("b", "a")])
    assert find_cycles([("a", "a")]) == [["a", "a"]]
    assert not find_cycles([("a", "b"), ("b", "c")])
    assert is_acyclic(["a", "b", "c"], [("a", "b"), ("b", "c")])
    assert not is_acyclic(["a", "b"], [("a", "b"), ("b", "a")])
    assert not is_acyclic(["a"], [("a", "a")])


# ----------------------------------------------------- committed graph artifact
def test_committed_lock_graph_is_valid_acyclic_and_fresh():
    path = f"{REPO}/benchmarks/lock_order_graph.json"
    assert check_lock_order_graph(path, root=REPO) == []


def test_lock_graph_schema_rejects_cyclic_and_malformed(tmp_path):
    base = {
        "schema": "lock_order_graph/v1",
        "generated_by": "test",
        "nodes": ["A", "B"],
    }
    cyclic = dict(base, edges=[
        {"from": "A", "to": "B", "sites": ["x.py"]},
        {"from": "B", "to": "A", "sites": ["x.py"]},
    ])
    p = tmp_path / "g.json"
    p.write_text(json.dumps(cyclic))
    errs = check_lock_order_graph(str(p))
    assert any("CYCLIC" in e for e in errs), errs

    dangling = dict(base, edges=[
        {"from": "A", "to": "C", "sites": ["x.py"]},
    ])
    p.write_text(json.dumps(dangling))
    errs = check_lock_order_graph(str(p))
    assert any("not in 'nodes'" in e for e in errs), errs

    ok = dict(base, edges=[{"from": "A", "to": "B", "sites": ["x.py"]}])
    p.write_text(json.dumps(ok))
    assert check_lock_order_graph(str(p)) == []

    p.write_text("{")
    assert check_lock_order_graph(str(p))


def test_lock_graph_freshness_detects_drift(tmp_path):
    # an artifact claiming zero locks against the real repo = stale
    p = tmp_path / "g.json"
    p.write_text(json.dumps({
        "schema": "lock_order_graph/v1", "generated_by": "test",
        "nodes": [], "edges": [],
    }))
    errs = check_lock_order_graph(str(p), root=REPO)
    assert any("stale" in e and "--write" in e for e in errs), errs


# ------------------------------------------------------------ runtime witness
_SEEDED_DEADLOCK_SRC = """
import threading


class Fix:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def forward(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def backward(self):
        with self.b_lock:
            with self.a_lock:
                pass
"""


def test_seeded_deadlock_caught_by_witness_and_static_pass():
    """The acceptance fixture: ONE seeded inversion, flagged by BOTH
    halves — the runtime witness when the nesting executes, the static
    pass when the equivalent source is linted."""
    from d4pg_tpu.analysis import lockwitness

    lockwitness.reset()
    lockwitness.enable()
    try:
        a = lockwitness.named_lock("Fix.a_lock")
        b = lockwitness.named_lock("Fix.b_lock")
        with a:            # the exact nesting _SEEDED_DEADLOCK_SRC encodes
            with b:
                pass
        with b:
            with a:
                pass
        with pytest.raises(lockwitness.LockOrderWitnessError) as ei:
            lockwitness.check_against({"nodes": ["Fix.a_lock", "Fix.b_lock"],
                                       "edges": []})
        assert "Fix.a_lock" in str(ei.value)
    finally:
        lockwitness.reset()
    findings, _ = lint_source(
        textwrap.dedent(_SEEDED_DEADLOCK_SRC), "d4pg_tpu/runtime/x.py",
        checks=["lock-order"],
    )
    assert findings, "static pass missed the seeded deadlock fixture"


def test_witness_consistent_nesting_passes_and_counts():
    from d4pg_tpu.analysis import lockwitness

    lockwitness.reset()
    lockwitness.enable()
    try:
        outer = lockwitness.named_condition("W.outer_cond")
        inner = lockwitness.named_lock("W.inner_lock")
        for _ in range(3):
            with outer:
                with inner:
                    pass
        summary = lockwitness.check_against({
            "nodes": ["W.outer_cond", "W.inner_lock"],
            "edges": [{"from": "W.outer_cond", "to": "W.inner_lock",
                       "sites": ["x.py"]}],
        })
        assert summary["contradictions"] == 0
        assert summary["observed_edges"] == 1
        assert summary["novel_edges"] == 0
        # a novel edge the static pass missed is tolerated, not fatal
        with inner:
            pass
        with outer:
            pass
    finally:
        lockwitness.reset()


def test_witness_reentrant_rlock_is_not_a_contradiction():
    """Regression: reentrant acquisition of one RLock object must record
    no self-edge (legal), while nesting two DIFFERENT instances sharing
    a node name is a real two-instance ordering hazard and stays fatal."""
    from d4pg_tpu.analysis import lockwitness

    lockwitness.reset()
    lockwitness.enable()
    try:
        r = lockwitness.named_rlock("R.r_lock")
        with r:
            with r:  # reentrant: same object
                pass
        assert lockwitness.check_against({"nodes": ["R.r_lock"],
                                          "edges": []})["contradictions"] == 0
        a = lockwitness.named_lock("Pair.p_lock")
        b = lockwitness.named_lock("Pair.p_lock")  # second INSTANCE
        with a:
            with b:
                pass
        with pytest.raises(lockwitness.LockOrderWitnessError):
            lockwitness.check_against({"nodes": ["Pair.p_lock"], "edges": []})
    finally:
        lockwitness.reset()


def test_witness_disabled_returns_plain_primitives():
    from d4pg_tpu.analysis import lockwitness

    lockwitness.reset()
    lock = lockwitness.named_lock("X.lock")
    assert type(lock).__name__ != "_Witnessed"
    with lock:
        pass
    assert lockwitness.observed_edges() == {}


def test_witness_condition_proxy_supports_wait_notify():
    from d4pg_tpu.analysis import lockwitness

    lockwitness.reset()
    lockwitness.enable()
    try:
        cond = lockwitness.named_condition("W.cond")
        hit = []

        def waiter():
            with cond:
                while not hit:
                    cond.wait(0.2)

        t = threading.Thread(target=waiter, name="w", daemon=True)
        t.start()
        time.sleep(0.05)
        with cond:
            hit.append(1)
            cond.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        lockwitness.reset()


def test_witness_names_in_product_code_match_static_graph_nodes():
    """Every named_lock/named_condition id wired into d4pg_tpu must BE a
    node of the committed static graph — the two halves share one
    identity space or the comparison is meaningless."""
    with open(f"{REPO}/benchmarks/lock_order_graph.json") as f:
        nodes = set(json.load(f)["nodes"])
    wired = set()
    for dirpath, _dirs, fnames in os.walk(f"{REPO}/d4pg_tpu"):
        for fn in fnames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in (
                        "named_lock", "named_rlock", "named_condition"
                    )
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                ):
                    wired.add(str(node.args[0].value))
    assert wired, "no witness wiring found in d4pg_tpu"
    missing = wired - nodes
    assert not missing, (
        f"witness names with no static-graph node: {sorted(missing)} — "
        "regenerate benchmarks/lock_order_graph.json or fix the name"
    )


# --------------------------------------------------------- partition coverage
def test_partition_gate_passes_on_real_model_zoo():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.d4pglint.wholeprog.partition_coverage"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "partition-coverage: OK" in proc.stdout


def test_partition_gate_fails_on_injected_undeclared_stack():
    """The PR-9 bug, seeded: an E=5 ensemble with its stack declaration
    withheld must be FLAGGED (the CLI exits 0 iff the gate caught it)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.d4pglint.wholeprog.partition_coverage",
         "--inject-undeclared-stack"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "caught" in proc.stdout


def test_explain_partition_rules_matches_shipping_matcher():
    """The audit's attribution and the shipping matcher share _leaf_spec;
    prove the specs agree leaf-for-leaf on a concrete tree."""
    import jax

    from d4pg_tpu.parallel.partition import (
        DEFAULT_RULES,
        explain_partition_rules,
        match_partition_rules,
    )

    params = {
        "hidden_0": {"kernel": np.zeros((8, 16)), "bias": np.zeros(16)},
        "hidden_1": {"kernel": np.zeros((16, 16))},
        "out": {"kernel": np.zeros((16, 4)), "bias": np.zeros(4)},
        # an UNdeclared 3-stack over a dense-written rule: rank mismatch
        # survives the stack gate and must replicate (the PR-9 shape)
        "hidden_2": {"kernel": np.zeros((3, 16, 16))},
    }
    specs = jax.tree_util.tree_leaves(
        match_partition_rules(DEFAULT_RULES, params),
        is_leaf=lambda x: hasattr(x, "index") or x == () or True,
    )
    rows = explain_partition_rules(DEFAULT_RULES, params)
    assert len(rows) == 6
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    by_name = {r["name"]: r for r in rows}
    from jax.sharding import PartitionSpec as P

    matched = match_partition_rules(DEFAULT_RULES, params)
    for path, _leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        node = matched
        for k in path:
            node = node[getattr(k, "key", k)]
        assert by_name[name]["spec"] == node, name
    assert by_name["hidden_2/kernel"]["outcome"] == "fallback_rank"
    assert by_name["hidden_2/kernel"]["spec"] == P()
    assert by_name["hidden_0/kernel"]["outcome"] == "rule"
    del specs  # silence linters: the tree comparison above is the check


# ---------------------------------------------------------------- docs drift
def test_docs_catalog_is_in_sync():
    assert check_docs(REPO) == []


def test_docs_drift_detected_when_row_or_heading_missing(tmp_path):
    with open(f"{REPO}/docs/analysis.md", encoding="utf-8") as f:
        text = f.read()
    # drop a check row
    p = tmp_path / "analysis.md"
    p.write_text("\n".join(
        l for l in text.splitlines() if not l.startswith("| `lock-order`")
    ))
    errs = check_docs(REPO, docs_path=str(p))
    assert any("`lock-order`" in e for e in errs), errs
    # drop a runtime-guard heading
    p.write_text(text.replace("### Lock-order witness", "### renamed"))
    errs = check_docs(REPO, docs_path=str(p))
    assert any("Lock-order witness" in e for e in errs), errs


# ------------------------------------------------- remaining analyzer sub-rules
def test_bounded_queue_put_without_admission_control_fires():
    bad = """
    class DynamicBatcher:
        def submit(self, req):
            self._queue.append(req)
    """
    findings, _ = lint_source(
        textwrap.dedent(bad), "d4pg_tpu/serve/batcher.py",
        checks=["thread-lifecycle"],
    )
    assert any("admission control" in f.message for f in findings), findings
    good = """
    class DynamicBatcher:
        def submit(self, req):
            if len(self._queue) >= self.queue_limit:
                raise ShedError("queue_full")
            self._queue.append(req)
    """
    findings, _ = lint_source(
        textwrap.dedent(good), "d4pg_tpu/serve/batcher.py",
        checks=["thread-lifecycle"],
    )
    assert findings == [], findings


# the shared conforming protocol model (single source: test_d4pglint.py)
from tests.test_d4pglint import PROTOCOL_GOOD_SRC as _MINIMAL_PROTOCOL  # noqa: E402


def test_protocol_silent_drop_branch_fires():
    server_bad = """
    from d4pg_tpu.serve import protocol


    class PolicyServer:
        def _serve_conn(self, conn):
            while True:
                frame = protocol.read_frame(conn)
                if frame is None:
                    return
                msg_type, req_id, payload = frame
                if msg_type == protocol.HEALTHZ:
                    continue
                if msg_type != protocol.ACT:
                    raise protocol.ProtocolError("bad")
                protocol.write_frame(conn, protocol.ACT_OK, req_id, payload)
    """
    findings, _ = lint_sources(
        {"d4pg_tpu/serve/protocol.py": textwrap.dedent(_MINIMAL_PROTOCOL),
         "d4pg_tpu/serve/server.py": textwrap.dedent(server_bad)},
        checks=["protocol-conformance"],
    )
    drops = [f for f in findings if "silent drop" in f.message]
    assert drops, findings
    assert drops[0].path == "d4pg_tpu/serve/server.py"


def test_protocol_raw_recv_outside_protocol_module_fires():
    client_bad = """
    def read_reply(sock):
        return sock.recv(4096)
    """
    findings, _ = lint_sources(
        {"d4pg_tpu/serve/protocol.py": textwrap.dedent(_MINIMAL_PROTOCOL),
         "d4pg_tpu/serve/client.py": textwrap.dedent(client_bad)},
        checks=["protocol-conformance"],
    )
    assert any(".recv()" in f.message for f in findings), findings


def test_unused_suppression_meta_comment_cannot_self_suppress():
    src = "x = 1  # d4pglint: disable=unused-suppression  -- nothing here\n"
    findings, _ = lint_source(
        src, "d4pg_tpu/runtime/x.py", checks=["unused-suppression"]
    )
    assert len(findings) == 1
    assert "unused-suppression" in findings[0].message


def test_unknown_check_id_in_suppression_is_flagged():
    src = "x = 1  # d4pglint: disable=wall-clock-dedline  -- typo'd id\n"
    findings, _ = lint_source(
        src, "d4pg_tpu/runtime/x.py", checks=["unused-suppression"]
    )
    assert len(findings) == 1
    assert "unknown check id" in findings[0].message


def test_repo_protocol_endpoints_manifest_is_not_stale():
    """Every PROTOCOL_ENDPOINTS row resolves to a real function in the
    real repo (a renamed receive loop must fail here, not silently
    un-check itself)."""
    from tools.d4pglint.core import parse_default_files
    from tools.d4pglint.wholeprog.config import PROTOCOL_ENDPOINTS
    from tools.d4pglint.wholeprog.protocolcheck import _function

    files = parse_default_files(REPO)
    for endpoint, (qual, _handled) in PROTOCOL_ENDPOINTS.items():
        assert _function(files, qual) is not None, (endpoint, qual)


# --------------------------------------------------- sweep-fix regression tests
def _fake_ingest_server(reply_type: int, ready: threading.Event, out: dict):
    """One-connection ingest impostor: real HELLO_OK handshake, then
    answers the first WINDOWS frame with ``reply_type``."""
    from d4pg_tpu.fleet import wire
    from d4pg_tpu.serve import protocol

    srv = socket.create_server(("127.0.0.1", 0))
    out["port"] = srv.getsockname()[1]
    ready.set()
    conn, _ = srv.accept()
    try:
        rfile = conn.makefile("rb")
        msg_type, req_id, payload = protocol.read_frame(rfile)
        assert msg_type == protocol.HELLO
        protocol.write_frame(
            conn, protocol.HELLO_OK, req_id,
            wire.encode_hello_ok(generation=0, max_windows=8, max_inflight=2),
        )
        msg_type, req_id, payload = protocol.read_frame(rfile)
        assert msg_type == protocol.WINDOWS
        protocol.write_frame(conn, reply_type, req_id, b"")
        time.sleep(0.5)  # let the client reader process before teardown
    finally:
        conn.close()
        srv.close()


def test_fleet_link_accounts_unexpected_reply_type_as_dropped():
    """Regression (whole-program sweep finding): an unexpected reply type
    for a known req_id popped the pending entry WITHOUT any ack callback,
    silently losing the frame from the emitted==accounted identity. The
    link must now count the windows dropped and die loudly."""
    from d4pg_tpu.fleet.actor import FleetLink
    from d4pg_tpu.serve import protocol

    ready = threading.Event()
    out: dict = {}
    srv = threading.Thread(
        target=_fake_ingest_server, args=(protocol.ACT_OK, ready, out),
        name="fake-ingest", daemon=True,
    )
    srv.start()
    assert ready.wait(5)

    acks: list = []
    link = FleetLink(
        "127.0.0.1", out["port"],
        dict(actor_id="t", env="e", obs_dim=4, action_dim=2, n_step=1,
             gamma=0.99, generation=0),
        on_ack=lambda kind, n: acks.append((kind, n)),
    )
    try:
        cols = {
            "obs": np.zeros((3, 4), np.float32),
            "action": np.zeros((3, 2), np.float32),
            "reward": np.zeros(3, np.float32),
            "next_obs": np.zeros((3, 4), np.float32),
            "discount": np.ones(3, np.float32),
        }
        assert link.acquire_credit(5.0)
        n = link.send_windows((0, 0, False), cols)
        assert n == 3
        deadline = time.monotonic() + 5.0
        while link.dead is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert link.dead is not None, "link survived an unexpected reply type"
        # the identity: every window of the frame is accounted, as dropped
        assert ("dropped", 3) in acks, acks
        assert link.inflight() == 0
    finally:
        link.close()
        srv.join(timeout=5)


def test_repo_lock_graph_has_the_known_cross_file_edges():
    """The committed graph carries the load-bearing cross-file nesting
    facts (trainer holds its buffer lock across replay's lock; the
    batcher's condition is held across the stats locks) — if resolution
    regresses to per-file only, these vanish and this test fails before
    the artifact quietly goes blind."""
    with open(f"{REPO}/benchmarks/lock_order_graph.json") as f:
        doc = json.load(f)
    pairs = {(e["from"], e["to"]) for e in doc["edges"]}
    assert ("Trainer._buffer_lock", "ReplayBuffer._lock") in pairs
    assert ("DynamicBatcher._cond", "ServeStats._lock") in pairs
    assert is_acyclic(doc["nodes"], list(pairs))
