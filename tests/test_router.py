"""Replica front-end (`serve/router.py`) over real sockets: dispatch and
spread, health-driven ejection / re-admission, bounded failover (the
accounting identity: every request answered ok / OVERLOADED / error —
never silently lost), canary rollout promote + rollback, and the
PolicyClient bounded-retry satellite.

The subprocess/CLI half of this surface lives in scripts/router_smoke.sh
(tests/test_router_smoke.py) and the chaos-soak router leg; everything
here is in-process so kill instants and reload instants are deterministic.
"""

import threading
import time

import jax
import numpy as np
import pytest

from bench import kill_policy_server_abruptly
from d4pg_tpu.agent import act_deterministic
from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.serve import (
    PolicyBundle,
    PolicyClient,
    PolicyServer,
    Router,
    protocol,
)
from d4pg_tpu.serve.batcher import ShedError
from d4pg_tpu.serve.bundle import actor_template, export_bundle, load_bundle
from d4pg_tpu.serve.client import ConnectionClosed, Overloaded

CFG = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(8, 8))
OBS = np.array([0.1, -0.2, 0.05, 0.3], np.float32)
PARAMS = actor_template(CFG)


def _bundle(params=None, path=None):
    return PolicyBundle(
        config=CFG,
        actor_params=params if params is not None else PARAMS,
        action_low=np.full(2, -1.0, np.float32),
        action_high=np.full(2, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "test"},
        path=path,
    )


def _ref(params, obs=OBS):
    return np.clip(
        np.asarray(act_deterministic(CFG, params, obs[None])[0]), -1.0, 1.0
    )


def _wait(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _server(bundle=None, port=0, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_wait_us", 200)
    kw.setdefault("watch_bundle", False)
    srv = PolicyServer(
        bundle if bundle is not None else _bundle(), port=port, **kw
    )
    srv.start()
    return srv


def _router(servers, **kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("readmit_after", 2)
    r = Router([("127.0.0.1", s.port) for s in servers], port=0, **kw)
    r.start()
    r.wait_for_replicas(len(servers), timeout_s=60)
    return r


def _drain_all(router, servers, killed=()):
    router.drain()
    for s in servers:
        if s not in killed:
            s.drain()


# --------------------------------------------------------------- dispatch
def test_roundtrip_spread_and_healthz():
    """Requests through the router match the direct forward; sequential
    traffic round-robins across both replicas (least-loaded ties rotate);
    router healthz carries the fleet view + the accounting surface."""
    servers = [_server() for _ in range(2)]
    router = _router(servers)
    try:
        ref = _ref(PARAMS)
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(10):
                np.testing.assert_allclose(c.act(OBS), ref, rtol=1e-5, atol=1e-6)
            h = c.healthz()
        assert h["router"] is True and h["status"] == "ok"
        assert h["admitted"] == 2 and len(h["replicas"]) == 2
        assert h["requests_total"] == 10
        assert h["answered_total"] == h["replies_ok"] == 10
        assert h["replies_overloaded"] == 0 and h["replies_error"] == 0
        # both replicas actually served (tie rotation, not lowest-index pin)
        assert all(r["ok"] >= 3 for r in h["replicas"]), h["replicas"]
        # the prober's replica view carries the healthz satellite fields
        for r in h["replicas"]:
            assert r["admitted"] is True and r["status"] == "ok"
            assert r["compile_count"] == 2  # buckets (1, 2), compiled once
            assert r["pid"] is not None
    finally:
        _drain_all(router, servers)


def test_replica_kill_mid_stream_fails_over_with_identity():
    """An abrupt replica death with requests in flight: every submitted
    request is still answered (bounded failover on the survivor), the dead
    replica is ejected, and nothing is silently lost."""
    servers = [_server() for _ in range(2)]
    # slow both device threads so the kill lands with requests IN FLIGHT
    for s in servers:
        real = s.batcher._infer

        def slow(p, o, _real=real):
            time.sleep(0.05)
            return _real(p, o)

        s.batcher._infer = slow
    router = _router(servers)
    try:
        with PolicyClient("127.0.0.1", router.port) as c:
            futs = [c.act_async(OBS) for _ in range(40)]
            time.sleep(0.1)  # several dispatched to each replica
            kill_policy_server_abruptly(servers[0])
            outcomes = {"ok": 0, "overloaded": 0}
            for f in futs:
                try:
                    f.result(60)
                    outcomes["ok"] += 1
                except Overloaded:
                    outcomes["overloaded"] += 1
            # the survivor absorbs everything the dead replica dropped
            assert outcomes["ok"] + outcomes["overloaded"] == 40
            assert outcomes["ok"] >= 30, outcomes
            # post-kill traffic flows on the survivor
            assert c.act(OBS, timeout=30).shape == (2,)
            h = c.healthz()
        assert h["requests_total"] == h["answered_total"] == 41
        assert h["retries"] >= 1  # in-flight work was actively rescued
        assert h["ejections"] >= 1
        dead = next(r for r in h["replicas"] if not r["admitted"])
        assert dead["ejected_reason"]
    finally:
        _drain_all(router, servers, killed=(servers[0],))


def test_all_replicas_ejected_router_answers_overloaded():
    servers = [_server()]
    router = _router(servers)
    try:
        with PolicyClient("127.0.0.1", router.port) as c:
            assert c.act(OBS).shape == (2,)
            kill_policy_server_abruptly(servers[0])
            _wait(
                lambda: router.healthz()["admitted"] == 0,
                msg="sole replica ejected",
            )
            with pytest.raises(Overloaded) as ei:
                c.act(OBS)
            assert "no_replicas" in str(ei.value)
            h = c.healthz()
        # the shed is ANSWERED — the identity holds through total outage
        assert h["requests_total"] == h["answered_total"] == 2
        assert h["replies_overloaded"] == 1
        assert h["status"] == "degraded"  # router alive, fleet gone
    finally:
        _drain_all(router, servers, killed=(servers[0],))


def test_restarted_replica_is_readmitted_after_k_probes():
    servers = [_server()]
    port = servers[0].port
    router = _router(servers, readmit_after=3)
    try:
        kill_policy_server_abruptly(servers[0])
        _wait(lambda: router.healthz()["admitted"] == 0, msg="ejection")
        restarted = _server(port=port)  # same address, fresh process state
        servers.append(restarted)
        _wait(lambda: router.healthz()["admitted"] == 1, msg="re-admission")
        h = router.healthz()
        assert h["replicas"][0]["healthy_streak"] >= 3
        kinds = [e["event"] for e in h["events_tail"]]
        assert "eject" in kinds and "admit" in kinds
        with PolicyClient("127.0.0.1", router.port) as c:
            np.testing.assert_allclose(
                c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
            )
    finally:
        _drain_all(router, servers, killed=(servers[0],))


def test_overloaded_replica_triggers_bounded_redispatch():
    """A replica that sheds (OVERLOADED) is retried on a different replica
    under the bounded budget — the client sees success, the router counts
    the retry."""
    servers = [_server() for _ in range(2)]

    def always_shed(obs, deadline_s=None):
        servers[0].stats.inc("shed_queue_full")
        raise ShedError("queue_full")

    servers[0].batcher.submit = always_shed
    router = _router(servers)
    try:
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(8):
                np.testing.assert_allclose(
                    c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
            h = c.healthz()
        assert h["replies_ok"] == 8 and h["replies_overloaded"] == 0
        assert h["retries"] >= 1  # ~half the picks landed on the shedder
    finally:
        _drain_all(router, servers)


# ---------------------------------------------------------------- canary
def _canary_fleet(tmp_path, chaos=None, params_new=None, break_canary=False,
                  **router_kw):
    """Two replicas serving on-disk bundles (watching them), a canary
    source dir with new params, and a router wired for rollout.
    ``break_canary`` deletes the source's params file BEFORE the router
    starts (bundle.json still present, so the rollout triggers) — the
    deploy-I/O-failure path."""
    import os

    dirs = [str(tmp_path / f"replica{i}") for i in range(2)]
    for d in dirs:
        export_bundle(d, CFG, PARAMS)
    canary_dir = str(tmp_path / "canary")
    export_bundle(
        canary_dir,
        CFG,
        params_new
        if params_new is not None
        else jax.tree_util.tree_map(lambda x: x + 0.5, PARAMS),
    )
    if break_canary:
        os.remove(os.path.join(canary_dir, "actor_params.npz"))
    servers = [
        _server(load_bundle(d), watch_bundle=True, poll_interval_s=0.05)
        for d in dirs
    ]
    router = Router(
        [("127.0.0.1", s.port) for s in servers],
        port=0,
        bundle_dirs=dirs,
        probe_interval_s=0.05,
        probe_timeout_s=1.0,
        readmit_after=2,
        canary_bundle=canary_dir,
        canary_fraction=0.5,
        canary_min_samples=5,
        canary_window=64,
        canary_attest_timeout_s=20.0,
        chaos=chaos,
        **router_kw,
    )
    router.start()
    router.wait_for_replicas(2, timeout_s=60)
    return servers, router, dirs


def test_canary_rollout_auto_promotes(tmp_path):
    """Healthy canary: deploy → observe (split traffic) → promote rolls
    every baseline forward, each attested — and the whole rollout swaps
    params on live replicas with zero recompiles."""
    servers, router, dirs = _canary_fleet(tmp_path)
    params_new = jax.tree_util.tree_map(lambda x: x + 0.5, PARAMS)
    try:
        state = lambda: router.healthz()["canary"]["state"]  # noqa: E731
        _wait(lambda: state() != "idle", msg="rollout start")
        ref_old, ref_new = _ref(PARAMS), _ref(params_new)
        with PolicyClient("127.0.0.1", router.port) as c:
            # drive traffic until the verdict: every reply is one of the
            # two param sets, never garbage
            for _ in range(400):
                a = c.act(OBS, timeout=30)
                assert np.allclose(a, ref_old, atol=1e-5) or np.allclose(
                    a, ref_new, atol=1e-5
                ), a
                if state() == "idle":
                    break
                time.sleep(0.01)
            _wait(lambda: state() == "idle", msg="rollout settle")
            h = c.healthz()
            assert h["canary_promotions"] == 1 and h["canary_rollbacks"] == 0
            # every replica attests the version its OWN dir now carries
            # (the version vector is per-replica: each roll-forward is its
            # own attested write into that replica's bundle dir)...
            import os

            for r, d in zip(h["replicas"], dirs):
                assert r["bundle_mtime"] == os.stat(
                    os.path.join(d, "bundle.json")
                ).st_mtime
            # ...and serves the new params, with the bucket programs intact
            for _ in range(4):
                np.testing.assert_allclose(
                    c.act(OBS), ref_new, rtol=1e-5, atol=1e-6
                )
        for s in servers:
            assert s.batcher.compile_count == 2  # zero recompiles
            assert s.stats.params_reloads >= 1
        kinds = [e["event"] for e in router.healthz()["events_tail"]]
        assert "canary_start" in kinds and "canary_promoted" in kinds
    finally:
        _drain_all(router, servers)


def test_corrupt_canary_rolls_back_baselines_never_reload(tmp_path):
    """The canary_corrupt chaos fault: the deployed params are truncated,
    the canary replica's reload fails (degraded → ejected), the router
    auto-rolls-back, the canary re-admits on the RESTORED bundle, and the
    baseline replica never reloads at all."""
    from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

    inj = ChaosInjector(ChaosPlan.parse("canary_corrupt@1"))
    servers, router, dirs = _canary_fleet(tmp_path, chaos=inj)
    try:
        _wait(
            lambda: router.stats.canary_rollbacks >= 1,
            msg="auto-rollback on corrupt canary",
        )
        assert inj.injections_total == 1
        _wait(
            lambda: router.healthz()["canary"]["state"] == "idle"
            and router.healthz()["admitted"] == 2,
            msg="rollback settle + re-admission",
        )
        h = router.healthz()
        assert h["canary_promotions"] == 0
        kinds = [e["event"] for e in h["events_tail"]]
        assert "canary_rollback" in kinds and "canary_rolled_back" in kinds
        # the canary is back on the old version; the baseline NEVER reloaded
        assert servers[0].stats.params_reloads == 0
        assert servers[0].healthz()["status"] == "ok"
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(6):
                np.testing.assert_allclose(
                    c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
        for s in servers:
            assert s.batcher.compile_count == 2  # zero recompiles throughout
    finally:
        _drain_all(router, servers)


def test_deploy_io_error_rolls_back_instead_of_split_brain(tmp_path):
    """A mid-deploy I/O failure (here: the canary source's params file
    vanishes between the mtime check and the copy) must route through the
    normal rollback — the touched replica is restored and re-ejected until
    it attests the old version — instead of stranding it on a half-deployed
    rollout with the state machine stuck in idle."""
    servers, router, dirs = _canary_fleet(tmp_path, break_canary=True)
    try:
        _wait(
            lambda: router.stats.canary_rollbacks >= 1,
            msg="rollback on deploy I/O error",
        )
        _wait(
            lambda: router.healthz()["canary"]["state"] == "idle"
            and router.healthz()["admitted"] == 2,
            msg="rollback settle + re-admission",
        )
        h = router.healthz()
        assert h["canary_promotions"] == 0
        events = h["events_tail"]
        rb = next(e for e in events if e["event"] == "canary_rollback")
        assert "deploy I/O error" in rb["reason"], rb
        assert any(e["event"] == "canary_rolled_back" for e in events)
        # baseline untouched; the restored canary serves the OLD params
        assert servers[0].stats.params_reloads == 0
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(6):
                np.testing.assert_allclose(
                    c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
    finally:
        _drain_all(router, servers)


def test_promote_io_error_rolls_back_whole_rollout(tmp_path):
    """The canary source vanishing DURING a rollout (after the canary
    deployed, before the promote step copies it to the baselines): the
    promote deploy raises, and the rollout must roll back — canary
    restored to the old bundle, baseline never touched — instead of
    spinning in 'promoting' forever."""
    import os

    servers, router, dirs = _canary_fleet(tmp_path)
    try:
        state = lambda: router.healthz()["canary"]["state"]  # noqa: E731
        _wait(lambda: state() != "idle", msg="rollout start")
        # canary (replica 1) is deployed by the tick that left idle; the
        # promote deploy to the baseline runs several ticks later (attest
        # + observe with min_samples of traffic) — break the source now
        os.remove(os.path.join(str(tmp_path / "canary"), "actor_params.npz"))
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(400):
                try:
                    c.act(OBS, timeout=30)
                except Overloaded:
                    # the rollback re-ejects EVERY touched replica, and
                    # this rollout touched both (canary + the backed-up
                    # promote target): a transient all-ejected window
                    # answering OVERLOADED(no_replicas) is the documented
                    # honest behavior, not a failure of this test
                    pass
                if router.stats.canary_rollbacks >= 1:
                    break
                time.sleep(0.01)
        _wait(
            lambda: router.stats.canary_rollbacks >= 1,
            msg="rollback on promote I/O error",
        )
        _wait(
            lambda: state() == "idle"
            and router.healthz()["admitted"] == 2,
            msg="rollback settle + re-admission",
        )
        h = router.healthz()
        # one rollout, one outcome: the promote VERDICT fired but the
        # rollout ended rolled back — it must never book a promotion too
        assert h["canary_promotions"] == 0 and h["canary_rollbacks"] == 1
        events = h["events_tail"]
        rb = next(e for e in events if e["event"] == "canary_rollback")
        assert "deploy I/O error during promote" in rb["reason"], rb
        # the promote target was backed up before its deploy failed, so
        # the rollback conservatively restores it (one reload of identical
        # old params — at most); the whole fleet ends on the OLD params
        assert servers[0].stats.params_reloads <= 1
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(6):
                np.testing.assert_allclose(
                    c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
        for s in servers:
            assert s.batcher.compile_count == 2  # zero recompiles throughout
    finally:
        _drain_all(router, servers)


def test_canary_fraction_must_leave_both_groups_traffic():
    """fraction 0 routes nothing to the canary and fraction 1 starves the
    baseline — either way the comparison windows can never BOTH fill and
    the rollout would observe forever. Refused at construction."""
    for bad in (0.0, 1.0):
        with pytest.raises(ValueError, match="canary-fraction"):
            Router(
                [("127.0.0.1", 1)],
                bundle_dirs=["/tmp/x"],
                canary_bundle="/tmp/y",
                canary_fraction=bad,
            )


def test_observation_starved_rollout_rolls_back(tmp_path):
    """A rollout whose comparison windows never fill (no traffic) must
    not wedge in 'observing' forever: the observe deadline rolls it back
    so canary traffic unfreezes and newer versions can roll out later."""
    servers, router, dirs = _canary_fleet(
        tmp_path, canary_observe_timeout_s=0.6
    )
    try:
        # no ACT traffic at all: min_samples can never be reached
        _wait(
            lambda: router.stats.canary_rollbacks >= 1,
            msg="starvation rollback",
        )
        _wait(
            lambda: router.healthz()["canary"]["state"] == "idle"
            and router.healthz()["admitted"] == 2,
            msg="rollback settle",
        )
        events = router.healthz()["events_tail"]
        rb = next(e for e in events if e["event"] == "canary_rollback")
        assert "observation starved" in rb["reason"], rb
        with PolicyClient("127.0.0.1", router.port) as c:
            np.testing.assert_allclose(
                c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
            )
    finally:
        _drain_all(router, servers)


def test_stuck_replica_is_ejected_and_requests_rescued():
    """A replica whose device thread wedges still answers healthz ok — the
    prober alone would never eject it and its dispatched requests would
    hang forever, breaking the accounting identity. The stuck watchdog
    (--stuck-after) ejects it; closing the dispatch link fails the hung
    futures over onto the survivor."""
    release = threading.Event()
    servers = [_server() for _ in range(2)]
    real = servers[0].batcher._infer

    def wedged(p, o, _real=real):
        release.wait(120)  # healthz stays "ok" the whole time
        return _real(p, o)

    servers[0].batcher._infer = wedged
    router = _router(servers, stuck_after_s=0.4)
    try:
        assert servers[0].healthz()["status"] == "ok"
        with PolicyClient("127.0.0.1", router.port) as c:
            futs = [c.act_async(OBS) for _ in range(8)]
            ref = _ref(PARAMS)
            for f in futs:  # every request rescued, none abandoned
                np.testing.assert_allclose(f.result(30), ref, rtol=1e-5,
                                           atol=1e-6)
            h = c.healthz()
        assert h["requests_total"] == h["answered_total"] == 8
        assert h["replies_ok"] == 8
        assert h["retries"] >= 1
        events = router.healthz()["events_tail"]
        assert any(
            e["event"] == "eject" and e["reason"] == "stuck" for e in events
        ), [e["event"] for e in events]
    finally:
        release.set()
        _drain_all(router, servers)


# --------------------------------------------- PolicyClient retry satellite
class _ScriptedBackend:
    """Minimal protocol speaker for client-retry tests: each accepted
    connection runs one scripted behavior ('reset' = abortive close on
    accept; else a list of per-ACT replies: 'overloaded' | 'ok')."""

    def __init__(self, scripts):
        import socket

        self._sock = socket.create_server(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._scripts = list(scripts)
        self._thread = threading.Thread(
            target=self._run, name="scripted-backend", daemon=True
        )
        self._thread.start()

    def _run(self):
        for script in self._scripts:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if script == "reset":
                # accept the link, take ONE request, then RST — the death
                # lands mid-request, after connect succeeded (closing at
                # accept races the client's connect itself)
                try:
                    protocol.read_frame(conn.makefile("rb"))
                except OSError:
                    pass
                protocol.abortive_close(conn)
                continue
            replies = list(script)
            try:
                rfile = conn.makefile("rb")
                while True:
                    frame = protocol.read_frame(rfile)
                    if frame is None:
                        break
                    _t, req_id, _p = frame
                    kind = replies.pop(0) if replies else "ok"
                    if kind == "overloaded":
                        protocol.write_frame(
                            conn, protocol.OVERLOADED, req_id, b"queue_full"
                        )
                    else:
                        protocol.write_frame(
                            conn,
                            protocol.ACT_OK,
                            req_id,
                            protocol.encode_action(
                                np.zeros(2, np.float32)
                            ),
                        )
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def test_client_retry_off_by_default_fast_fails():
    backend = _ScriptedBackend([["overloaded", "ok"]])
    try:
        with PolicyClient("127.0.0.1", backend.port) as c:
            with pytest.raises(Overloaded):
                c.act(OBS)  # historical semantics: the shed is surfaced
            assert c.act(OBS).shape == (2,)  # next call is not poisoned
    finally:
        backend.close()


def test_client_bounded_retry_rides_through_shed():
    backend = _ScriptedBackend([["overloaded", "overloaded", "ok"]])
    try:
        with PolicyClient(
            "127.0.0.1", backend.port, retries=2, retry_seed=0
        ) as c:
            assert c.act(OBS).shape == (2,)
    finally:
        backend.close()


def test_client_retry_budget_is_bounded():
    backend = _ScriptedBackend([["overloaded"] * 8])
    try:
        with PolicyClient(
            "127.0.0.1", backend.port, retries=1, retry_seed=0
        ) as c:
            with pytest.raises(Overloaded):
                c.act(OBS)  # 1 retry = 2 attempts, both shed → surfaced
    finally:
        backend.close()


def test_client_retry_redials_a_dead_link():
    """ConnectionClosed mid-request: the retry path tears down the dead
    link and redials — the SECOND connection serves the request."""
    backend = _ScriptedBackend(["reset", ["ok"]])
    try:
        c = PolicyClient("127.0.0.1", backend.port, retries=3, retry_seed=0)
        try:
            assert c.act(OBS, timeout=10).shape == (2,)
        finally:
            c.close()
    finally:
        backend.close()


def test_client_retry_zero_keeps_connectionclosed_fatal():
    backend = _ScriptedBackend(["reset"])
    try:
        c = PolicyClient("127.0.0.1", backend.port)
        try:
            with pytest.raises((ConnectionClosed, OSError)):
                c.act(OBS, timeout=10)
        finally:
            c.close()
    finally:
        backend.close()


def test_client_close_is_final_even_with_retries():
    """close() must stay final for a retry-enabled client: a later act()
    fails fast with ConnectionClosed instead of the retry path re-dialing
    a fresh socket + reader thread nobody will ever tear down."""
    backend = _ScriptedBackend([["ok"]])
    try:
        c = PolicyClient("127.0.0.1", backend.port, retries=2)
        np.testing.assert_allclose(
            c.act(np.zeros(4, np.float32), timeout=10), np.zeros(2)
        )
        reader = c._reader
        c.close()
        reader.join(timeout=10)
        with pytest.raises(ConnectionClosed):
            c.act(OBS, timeout=10)
        assert c._reader is reader  # no resurrected link
    finally:
        backend.close()


# ------------------------------------------------- healthz prober surface
def test_healthz_prober_fields_and_replica_id(tmp_path):
    """The satellite fields the router's prober needs: bundle_mtime (the
    serving version vector), inflight, uptime_s, compile_count, pid — plus
    --replica-id stamped into healthz AND the metrics row."""
    import os

    d = str(tmp_path / "b")
    export_bundle(d, CFG, PARAMS)
    srv = _server(load_bundle(d), replica_id=3, watch_bundle=True,
                  poll_interval_s=3600.0)
    try:
        with PolicyClient("127.0.0.1", srv.port) as c:
            c.act(OBS)
        h = protocol.probe_healthz("127.0.0.1", srv.port)
        assert h["status"] == "ok"
        assert h["bundle_mtime"] == os.stat(
            os.path.join(d, "bundle.json")
        ).st_mtime
        assert h["inflight"] == 0  # gauge returns to rest after completion
        assert h["uptime_s"] > 0
        assert h["compile_count"] == 2
        assert h["replica_id"] == 3
        assert h["pid"] == os.getpid()  # in-process server
        assert srv._metrics_row()["replica_id"] == 3.0
    finally:
        srv.drain()


def test_bundle_mtime_attests_only_successful_reloads(tmp_path):
    """Satellite regression: a FAILED bundle reload must not advance the
    healthz version vector (the canary controller would promote a rollout
    nobody loaded), and the degraded status must clear on the next
    successful reload — not stick."""
    import os

    d = str(tmp_path / "b")
    export_bundle(d, CFG, PARAMS)
    srv = _server(load_bundle(d), watch_bundle=True, poll_interval_s=3600.0)
    try:
        m0 = srv.healthz()["bundle_mtime"]
        # corrupt re-export: truncated params + advanced json mtime
        pfile = os.path.join(d, "actor_params.npz")
        with open(pfile, "rb+") as f:
            f.truncate(os.path.getsize(pfile) // 2)
        os.utime(
            os.path.join(d, "bundle.json"), (time.time() + 2, time.time() + 2)
        )
        assert srv.check_reload() is False
        h = srv.healthz()
        assert h["status"] == "degraded"
        assert h["bundle_mtime"] == m0  # version vector did NOT move
        # a subsequent good export clears degraded and attests the new one
        params_new = jax.tree_util.tree_map(lambda x: x + 0.25, PARAMS)
        export_bundle(d, CFG, params_new)
        os.utime(
            os.path.join(d, "bundle.json"), (time.time() + 4, time.time() + 4)
        )
        assert srv.check_reload() is True
        h = srv.healthz()
        assert h["status"] == "ok"
        assert h["bundle_mtime"] == os.stat(
            os.path.join(d, "bundle.json")
        ).st_mtime
        assert h["bundle_mtime"] != m0
        with PolicyClient("127.0.0.1", srv.port) as c:
            np.testing.assert_allclose(
                c.act(OBS), _ref(params_new), rtol=1e-5, atol=1e-6
            )
    finally:
        srv.drain()
