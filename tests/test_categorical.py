"""Unit tests for the categorical projection against a NumPy oracle.

The oracle is an independent per-sample, per-atom loop implementing
Φ(R + γ_eff·z) from the C51/D4PG papers (the reference's own two
implementations disagree on n-step discounting — SURVEY.md §4 — so the oracle,
not the reference, pins correctness).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.ops import (
    categorical_projection,
    categorical_td_loss,
    expected_value,
    make_support,
)


def oracle_projection(v_min, v_max, num_atoms, probs, rewards, discounts):
    z = np.linspace(v_min, v_max, num_atoms)
    delta = (v_max - v_min) / (num_atoms - 1)
    out = np.zeros_like(probs)
    for i in range(probs.shape[0]):
        for j in range(num_atoms):
            tz = np.clip(rewards[i] + discounts[i] * z[j], v_min, v_max)
            b = (tz - v_min) / delta
            lo, hi = int(np.floor(b)), int(np.ceil(b))
            if lo == hi:
                out[i, lo] += probs[i, j]
            else:
                out[i, lo] += probs[i, j] * (hi - b)
                out[i, hi] += probs[i, j] * (b - lo)
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_projection_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    batch, atoms = 32, 51
    support = make_support(-10.0, 10.0, atoms)
    logits = rng.normal(size=(batch, atoms))
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    rewards = rng.uniform(-15, 15, size=batch)
    # Mix of terminal (0), full n-step (gamma^n), and truncated windows.
    discounts = rng.choice([0.0, 0.99**5, 0.99**2, 0.99], size=batch)

    got = np.asarray(
        categorical_projection(
            support,
            jnp.asarray(probs, jnp.float32),
            jnp.asarray(rewards, jnp.float32),
            jnp.asarray(discounts, jnp.float32),
        )
    )
    want = oracle_projection(-10.0, 10.0, atoms, probs, rewards, discounts)
    np.testing.assert_allclose(got, want, atol=1e-5)
    # Projection conserves probability mass.
    np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-5)


def test_terminal_collapses_to_reward_atom():
    support = make_support(-10.0, 10.0, 21)  # delta = 1.0, atoms at integers
    probs = jnp.ones((1, 21)) / 21.0
    out = categorical_projection(
        support, probs, jnp.asarray([-3.0]), jnp.asarray([0.0])
    )
    expected = np.zeros(21)
    expected[7] = 1.0  # atom for value -3
    np.testing.assert_allclose(np.asarray(out[0]), expected, atol=1e-6)


def test_reward_clipping_to_support_edges():
    support = make_support(-1.0, 1.0, 5)
    probs = jnp.ones((2, 5)) / 5.0
    out = categorical_projection(
        support, probs, jnp.asarray([100.0, -100.0]), jnp.asarray([0.0, 0.0])
    )
    np.testing.assert_allclose(np.asarray(out[0]), [0, 0, 0, 0, 1], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), [1, 0, 0, 0, 0], atol=1e-6)


def test_identity_projection():
    # r=0, discount=1 maps every atom onto itself.
    support = make_support(-2.0, 2.0, 9)
    rng = np.random.default_rng(3)
    p = rng.dirichlet(np.ones(9), size=4).astype(np.float32)
    out = categorical_projection(
        support, jnp.asarray(p), jnp.zeros(4), jnp.ones(4)
    )
    np.testing.assert_allclose(np.asarray(out), p, atol=1e-5)


def test_projection_is_jittable_and_grads_flow():
    support = make_support(-5.0, 5.0, 11)

    @jax.jit
    def loss_fn(logits):
        probs = jax.nn.softmax(logits)
        proj = categorical_projection(
            support, probs, jnp.ones(4) * 0.5, jnp.full(4, 0.99)
        )
        loss, per = categorical_td_loss(logits, proj)
        return loss

    g = jax.grad(loss_fn)(jnp.zeros((4, 11)))
    assert np.all(np.isfinite(np.asarray(g)))


def test_td_loss_matches_manual_ce():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(8, 51)), jnp.float32)
    target = jnp.asarray(rng.dirichlet(np.ones(51), size=8), jnp.float32)
    w = jnp.asarray(rng.uniform(0.5, 1.0, size=8), jnp.float32)
    loss, per = categorical_td_loss(logits, target, w)
    p = np.asarray(jax.nn.softmax(logits))
    manual = -(np.asarray(target) * np.log(p)).sum(-1)
    np.testing.assert_allclose(np.asarray(per), manual, rtol=1e-5)
    np.testing.assert_allclose(
        float(loss), float((np.asarray(w) * manual).mean()), rtol=1e-5
    )


def test_expected_value():
    support = make_support(0.0, 10.0, 11)
    probs = jnp.zeros((1, 11)).at[0, 3].set(1.0)
    assert float(expected_value(support, probs)[0]) == pytest.approx(3.0)
