"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-device DP/psum paths are tested without TPU hardware via
``--xla_force_host_platform_device_count=8`` (SURVEY.md §4).

FAST-TIER BUDGET (round-4 audit): ``pytest -m "not slow"`` must stay
under ~3 minutes on a 1-core host. JAX CPU compiles dominate test time,
so anything that compiles a physics step (planar/spatial dynamics — the
mass-matrix Hessian alone is tens of seconds), builds a full Trainer, or
traces a DP/TP shard_map belongs in ``slow`` unless it is THE smoke test
for its subsystem (one end-to-end Trainer test stays fast on purpose).
Measured 2026-08 (1-core host, a TPU training run sharing the core):
~18 min before the audit, 280 s after — the residual floor is JAX import
+ one small jit per test file; expect ≤2-3 min on an idle host. When
adding a test, check its wall time with ``--durations=0`` before leaving
it unmarked.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")
