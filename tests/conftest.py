"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-device DP/psum paths are tested without TPU hardware via
``--xla_force_host_platform_device_count=8`` (SURVEY.md §4).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")
