"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-device DP/psum paths are tested without TPU hardware via
``--xla_force_host_platform_device_count=8`` (SURVEY.md §4).

FAST-TIER BUDGET (round-4 audit): ``pytest -m "not slow"`` must stay
under ~3 minutes on a 1-core host. JAX CPU compiles dominate test time,
so anything that compiles a physics step (planar/spatial dynamics — the
mass-matrix Hessian alone is tens of seconds), builds a full Trainer, or
traces a DP/TP shard_map belongs in ``slow`` unless it is THE smoke test
for its subsystem (one end-to-end Trainer test stays fast on purpose).
Measured 2026-08 (1-core host, a TPU training run sharing the core):
~18 min before the audit, 280 s after — the residual floor is JAX import
+ one small jit per test file; expect ≤2-3 min on an idle host. When
adding a test, check its wall time with ``--durations=0`` before leaving
it unmarked.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")

import functools  # noqa: E402

import pytest  # noqa: E402


def clean_cpu_env(*, pythonpath_repo: bool = False) -> dict:
    """A child-process env with a REAL local CPU backend: the
    tunneled-TPU plugin registers itself via PYTHONPATH site hooks and
    AXON_*/TPU_* vars and overrides JAX_PLATFORMS=cpu (a per-step host
    sync then costs a ~100 ms link round-trip — per-step env loops crawl
    ~1000x). ONE copy here: every subprocess smoke (serve, fleet, dmc)
    and the EGL probe scrub the same vars or their scrub rules diverge.
    ``pythonpath_repo=True`` also drops the inherited PYTHONPATH (where
    the plugin's site hooks live) and pins it to the repo root so the
    child can still import d4pg_tpu."""
    drop = {"JAX_PLATFORMS", "XLA_FLAGS"}
    if pythonpath_repo:
        drop.add("PYTHONPATH")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in drop and "AXON" not in k and "TPU" not in k
    }
    env["JAX_PLATFORMS"] = "cpu"
    if pythonpath_repo:
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
    return env


@functools.lru_cache(maxsize=1)
def has_working_egl() -> bool:
    """True iff an EGL context can be created and a frame rendered, probed
    in a fresh interpreter with ``MUJOCO_GL=egl`` forced (cached per
    session). Subprocess on purpose: merely importing ``OpenGL.EGL`` can
    succeed on a box whose driver then fails at context creation, and a
    failed probe must not poison this process's GL/dm_control import
    state. Lazy on purpose: the hook below only calls this when an
    ``egl``-marked test is actually about to RUN, so a tier-1 pass that
    deselects them (they are all ``slow``) never pays the probe."""
    import os
    import subprocess
    import sys

    probe = (
        "import os; os.environ['MUJOCO_GL'] = 'egl'; "
        "from dm_control import suite; "
        "e = suite.load('cartpole', 'swingup'); e.reset(); "
        "e.physics.render(16, 16); print('EGL_OK')"
    )
    env = clean_cpu_env()
    env["MUJOCO_GL"] = "egl"
    try:
        p = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True, text=True, timeout=180, env=env,
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    return "EGL_OK" in p.stdout


def pytest_runtest_setup(item):
    if item.get_closest_marker("egl") is not None and not has_working_egl():
        pytest.skip("no working EGL/GL stack on this image (capability probe)")
