"""Planar physics engine: quantitative validation against MuJoCo + the
on-device HalfCheetah env built on it.

The rigid-body dynamics (mass matrix, bias forces, FK) must MATCH the host
MuJoCo compiled from the same MJCF — that is the correctness bar for the
Lagrangian-autodiff formulation. Contacts are penalty-based by design
(documented deviation), validated behaviorally: the passive cheetah settles
on its feet at the same height MuJoCo finds, and stays finite under
bang-bang torques.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

mujoco = pytest.importorskip("mujoco")

from d4pg_tpu.envs.locomotion import HalfCheetah, _gym_xml
from d4pg_tpu.envs.planar import (
    bias_force,
    body_coms,
    contact_points,
    extract_planar_model,
    mass_matrix,
    step_physics,
)

XML = _gym_xml("half_cheetah.xml")


@pytest.fixture(scope="module")
def model():
    return extract_planar_model(XML)


@pytest.fixture(scope="module")
def mj():
    m = mujoco.MjModel.from_xml_path(XML)
    return m, mujoco.MjData(m)


def _random_state(rng):
    q = rng.uniform(-0.6, 0.6, 9)
    q[0] = rng.uniform(-1, 1)
    q[1] = rng.uniform(0.2, 1.0)  # airborne: rigid-body terms only
    qd = rng.normal(0, 1.0, 9)
    return q, qd


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_mass_matrix_matches_mujoco(model, mj):
    m, d = mj
    rng = np.random.default_rng(0)
    for _ in range(5):
        q, qd = _random_state(rng)
        d.qpos[:], d.qvel[:] = q, qd
        mujoco.mj_forward(m, d)
        M_mj = np.zeros((9, 9))
        mujoco.mj_fullM(m, d, M_mj)
        M_ours = np.asarray(mass_matrix(model, jnp.asarray(q)))
        # f32 engine vs f64 MuJoCo: agreement to f32 resolution
        np.testing.assert_allclose(M_ours, M_mj, atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_bias_force_matches_mujoco_rne(model, mj):
    """Coriolis + centrifugal + gravity == mj_rne(flg_acc=0)."""
    m, d = mj
    rng = np.random.default_rng(1)
    for _ in range(5):
        q, qd = _random_state(rng)
        d.qpos[:], d.qvel[:] = q, qd
        mujoco.mj_forward(m, d)
        bias_mj = np.zeros(9)
        mujoco.mj_rne(m, d, 0, bias_mj)
        bias_ours = np.asarray(bias_force(model, jnp.asarray(q), jnp.asarray(qd)))
        np.testing.assert_allclose(bias_ours, bias_mj, atol=5e-3, rtol=1e-3)


def test_fk_coms_match_mujoco(model, mj):
    m, d = mj
    rng = np.random.default_rng(2)
    q, qd = _random_state(rng)
    d.qpos[:], d.qvel[:] = q, qd
    mujoco.mj_forward(m, d)
    coms, _ = body_coms(model, jnp.asarray(q))
    np.testing.assert_allclose(
        np.asarray(coms), d.xipos[1:][:, [0, 2]], atol=1e-5
    )


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_passive_drop_settles_like_mujoco(model, mj):
    """Contact model check: from qpos0 the cheetah must come to rest on its
    feet at (approximately) the height/pitch real MuJoCo finds."""
    m, _ = mj
    d = mujoco.MjData(m)
    for _ in range(300):
        mujoco.mj_step(m, d)

    @jax.jit
    def roll(q, qd):
        def body(c, _):
            q, qd = c
            q, qd = step_physics(model, q, qd, jnp.zeros(6), 4, 0.0025)
            return (q, qd), None

        (q, qd), _ = jax.lax.scan(body, (q, qd), None, length=300)
        return q, qd

    q, qd = roll(jnp.zeros(9), jnp.zeros(9))
    assert bool(jnp.all(jnp.isfinite(q)))
    # settle height/pitch within 2 cm / 0.05 rad of MuJoCo's
    np.testing.assert_allclose(float(q[1]), d.qpos[1], atol=0.02)
    np.testing.assert_allclose(float(q[2]), d.qpos[2], atol=0.05)
    # at rest
    assert float(jnp.max(jnp.abs(qd))) < 0.1
    # standing on contact points, not sunk: worst penetration < 1.5 cm
    gaps = np.asarray(contact_points(model, q))[:, 1] - np.asarray(
        model.con_radius
    )
    assert gaps.min() > -0.015


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_bang_bang_torques_stay_finite(model):
    """Penalty contacts + semi-implicit Euler must not explode under
    full-gear bang-bang actuation (the stress case for penalty methods)."""

    @functools.partial(jax.jit, static_argnums=2)
    def roll(q, qd, n, key):
        def body(c, k):
            q, qd = c
            tau = jax.random.choice(k, jnp.asarray([-1.0, 1.0]), (6,))
            q, qd = step_physics(model, q, qd, tau, 4, 0.0025)
            return (q, qd), jnp.max(jnp.abs(qd))

        keys = jax.random.split(key, n)
        (q, qd), maxv = jax.lax.scan(body, (q, qd), keys)
        return q, qd, maxv

    q, qd, maxv = roll(jnp.zeros(9), jnp.zeros(9), 500, jax.random.PRNGKey(0))
    assert bool(jnp.all(jnp.isfinite(q))) and bool(jnp.all(jnp.isfinite(qd)))
    # velocity scale comparable to MuJoCo under the same regime (~22 rad/s)
    assert float(jnp.max(maxv)) < 60.0


@pytest.mark.parametrize("asset", ["hopper.xml", "walker2d.xml"])
@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_hopper_walker_dynamics_match_mujoco(asset):
    """The same Lagrangian machinery is exact for every planar MJCF: mass
    matrix + bias vs MuJoCo on the other two gym planar models (these use
    joint ref offsets — qpos0 ≠ 0 — which cheetah doesn't exercise)."""
    xml = _gym_xml(asset)
    model = extract_planar_model(xml)
    m = mujoco.MjModel.from_xml_path(xml)
    d = mujoco.MjData(m)
    rng = np.random.default_rng(3)
    nq = m.nq
    for _ in range(3):
        q = np.asarray(m.qpos0) + rng.uniform(-0.4, 0.4, nq)
        q[1] = 1.25 + rng.uniform(0.0, 0.5)  # airborne
        qd = rng.normal(0, 1.0, nq)
        d.qpos[:], d.qvel[:] = q, qd
        mujoco.mj_forward(m, d)
        M_mj = np.zeros((nq, nq))
        mujoco.mj_fullM(m, d, M_mj)
        np.testing.assert_allclose(
            np.asarray(mass_matrix(model, jnp.asarray(q))), M_mj,
            atol=2e-4, rtol=2e-4,
        )
        bias_mj = np.zeros(nq)
        mujoco.mj_rne(m, d, 0, bias_mj)
        np.testing.assert_allclose(
            np.asarray(bias_force(model, jnp.asarray(q), jnp.asarray(qd))),
            bias_mj, atol=5e-3, rtol=1e-3,
        )


class TestHopperWalkerEnvs:
    def test_hopper_shapes_and_healthy_termination(self):
        from d4pg_tpu.envs.locomotion import Hopper

        env = Hopper()
        state, obs = env.reset(jax.random.PRNGKey(0))
        assert obs.shape == (11,)
        # starts healthy at the XML pose (z ≈ 1.25)
        q, qd = state.physics
        assert float(q[1]) > 1.2
        step = jax.jit(env.step)
        state2, obs2, r, term, trunc = step(state, jnp.zeros(3))
        assert float(term) == 0.0
        # healthy bonus present: standing still with zero ctrl earns ~1.0
        assert 0.5 < float(r) < 1.5
        # force an unhealthy state (fallen over): terminates
        fallen = state._replace(
            physics=(q.at[1].set(0.5).at[2].set(0.5), qd)
        )
        _, _, _, term2, _ = step(fallen, jnp.zeros(3))
        assert float(term2) == 1.0

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_walker_shapes_and_healthy_termination(self):
        from d4pg_tpu.envs.locomotion import Walker2d

        env = Walker2d()
        state, obs = env.reset(jax.random.PRNGKey(0))
        assert obs.shape == (17,)
        q, qd = state.physics
        step = jax.jit(env.step)
        _, _, r, term, _ = step(state, jnp.zeros(6))
        assert float(term) == 0.0 and 0.5 < float(r) < 1.5
        fallen = state._replace(physics=(q.at[1].set(0.3), qd))
        _, _, _, term2, _ = step(fallen, jnp.zeros(6))
        assert float(term2) == 1.0

    def test_registry(self):
        from d4pg_tpu.envs import make_env
        from d4pg_tpu.envs.locomotion import Hopper, Walker2d

        assert isinstance(make_env("hopper", None), Hopper)
        assert isinstance(make_env("walker2d", None), Walker2d)


class TestHalfCheetahEnv:
    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_reset_and_step_shapes_jit_vmap(self):
        env = HalfCheetah()
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        states, obs = jax.vmap(env.reset)(keys)
        assert obs.shape == (3, 17)
        actions = jnp.zeros((3, 6))
        states2, obs2, r, term, trunc = jax.vmap(env.step)(states, actions)
        assert obs2.shape == (3, 17) and r.shape == (3,)
        assert bool(jnp.all(term == 0.0))
        # reset noise: different keys → different initial states
        assert not np.allclose(np.asarray(obs[0]), np.asarray(obs[1]))

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_reward_is_forward_velocity_minus_ctrl_cost(self):
        env = HalfCheetah()
        state, _ = env.reset(jax.random.PRNGKey(0))
        a = jnp.full((6,), 0.5)
        q0 = state.physics[0]
        state2, _, r, _, _ = jax.jit(env.step)(state, a)
        x_vel = (state2.physics[0][0] - q0[0]) / 0.05
        expect = 1.0 * x_vel - 0.1 * float(jnp.sum(a**2))
        np.testing.assert_allclose(float(r), expect, rtol=1e-5)

    def test_obs_layout_matches_gym_v5(self):
        env = HalfCheetah()
        state, obs = env.reset(jax.random.PRNGKey(3))
        q, qd = state.physics
        np.testing.assert_allclose(np.asarray(obs[:8]), np.asarray(q[1:]))
        np.testing.assert_allclose(np.asarray(obs[8:]), np.asarray(qd))

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_truncates_at_max_episode_steps(self):
        env = HalfCheetah(max_episode_steps=3)
        state, _ = env.reset(jax.random.PRNGKey(0))
        step = jax.jit(env.step)
        for i in range(3):
            state, _, _, term, trunc = step(state, jnp.zeros(6))
        assert float(trunc) == 1.0 and float(term) == 0.0

    @pytest.mark.slow
    def test_standing_episode_return_scale(self):
        """Zero-action episode: the cheetah settles and drifts little —
        |return| stays near zero, the same scale gym reports for a passive
        policy (sanity that reward is not degenerate)."""
        from d4pg_tpu.envs.rollouts import rollout

        env = HalfCheetah(max_episode_steps=200)
        policy = lambda obs, key: jnp.zeros(6)
        _, _, traj = rollout(env, policy, jax.random.PRNGKey(0), num_steps=200)
        ret = float(jnp.sum(traj.reward))
        assert np.isfinite(ret) and abs(ret) < 50.0

    @pytest.mark.slow
    def test_on_device_trainer_over_cpu_mesh(self):
        """Flagship on-device loop (rollout + device PER + train scan) with
        the planar HalfCheetah, data-parallel over the 8-device virtual CPU
        mesh — the CPU-mesh validation VERDICT round-1 asked for."""
        from d4pg_tpu.agent import D4PGConfig, create_train_state
        from d4pg_tpu.models.critic import DistConfig
        from d4pg_tpu.parallel import make_mesh
        from d4pg_tpu.parallel.dp import replicate
        from d4pg_tpu.runtime.on_device import make_on_device_trainer

        mesh = make_mesh(dp=8, tp=1)
        config = D4PGConfig(
            obs_dim=17, action_dim=6, hidden_sizes=(32, 32), n_step=5,
            prioritized=True,
            dist=DistConfig(kind="categorical", num_atoms=51,
                            v_min=-100.0, v_max=1500.0),
        )
        init_fn, warm_fn, it_fn = make_on_device_trainer(
            config, HalfCheetah(), num_envs=16, segment_len=8,
            replay_capacity=512, batch_size=64, train_steps_per_iter=2,
            mesh=mesh,
        )
        state = replicate(create_train_state(config, jax.random.PRNGKey(0)), mesh)
        carry = warm_fn(init_fn(state, jax.random.PRNGKey(1)), 1.0)
        carry, m = it_fn(carry, 1.0)
        assert np.isfinite(float(m["critic_loss"]))
        # params stay replicated bit-identical across the mesh
        p = carry[0].actor_params
        leaf = jax.tree_util.tree_leaves(p)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

    def test_registry_and_preset(self):
        from d4pg_tpu.config import ENV_PRESETS, TrainConfig, apply_env_preset
        from d4pg_tpu.envs import make_env

        env = make_env("halfcheetah", None)
        assert isinstance(env, HalfCheetah)
        cfg = apply_env_preset(TrainConfig(env="halfcheetah"))
        assert cfg.agent.obs_dim == 17 and cfg.agent.action_dim == 6
        assert ENV_PRESETS["halfcheetah"]["v_max"] == 1000.0
