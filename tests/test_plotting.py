"""Offline plotting tools (SURVEY.md §2 #22: plots/plots.py + notebook Logger)."""

import json
import os

import numpy as np
import pytest

from d4pg_tpu.utils.plotting import available_metrics, compare_runs, ewma, load_run


def ewma_oracle(data, window):
    # direct recurrence, the semantics plots/plots.py:6-21 computes
    alpha = 2.0 / (window + 1.0)
    out = np.empty(len(data))
    out[0] = data[0]
    for t in range(1, len(data)):
        out[t] = (1 - alpha) * out[t - 1] + alpha * data[t]
    return out


class TestEwma:
    def test_matches_oracle(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=500)
        np.testing.assert_allclose(ewma(x, 20), ewma_oracle(x, 20), rtol=1e-12)

    def test_long_run_stable(self):
        # the reference's pow-based version underflows (1-α)^n for n ≫ 1/α;
        # ours must stay finite and track the signal on 100k points
        x = np.ones(100_000) * 5.0
        y = ewma(x, 10)
        assert np.all(np.isfinite(y))
        np.testing.assert_allclose(y, 5.0)

    def test_edge_cases(self):
        assert ewma(np.array([]), 5).size == 0
        np.testing.assert_allclose(ewma(np.array([3.0]), 5), [3.0])
        with pytest.raises(ValueError):
            ewma(np.zeros((3, 3)), 5)
        with pytest.raises(ValueError):
            ewma(np.zeros(4), 0)


@pytest.fixture
def run_dir(tmp_path):
    d = tmp_path / "run_a"
    d.mkdir()
    with open(d / "metrics.jsonl", "w") as f:
        for i in range(10):
            f.write(json.dumps({"step": i * 100, "t": i * 1.5,
                                "critic_loss": 1.0 / (i + 1)}) + "\n")
            if i % 2 == 0:
                f.write(json.dumps({"step": i * 100, "t": i * 1.5 + 0.1,
                                    "avg_test_reward": -200.0 + 10 * i}) + "\n")
    return str(d)


class TestLoadRun:
    def test_columns_and_axes(self, run_dir):
        run = load_run(run_dir)
        assert set(available_metrics(run)) == {"critic_loss", "avg_test_reward"}
        assert run["critic_loss"].shape == (10,)
        # eval rows are sparser and keep their own x-axes
        assert run["avg_test_reward"].shape == (5,)
        np.testing.assert_allclose(run["avg_test_reward/step"],
                                   [0, 200, 400, 600, 800])
        assert run["avg_test_reward/t"][0] == pytest.approx(0.1)

    def test_logger_roundtrip(self, tmp_path):
        # what MetricsLogger writes, load_run reads
        from d4pg_tpu.runtime.metrics import MetricsLogger

        d = str(tmp_path / "rt")
        logger = MetricsLogger(d, use_tensorboard=False)
        logger.log(1, {"a": 1.0})
        logger.log(2, {"a": 2.0, "b": 0.5})
        logger.close()
        run = load_run(d)
        np.testing.assert_allclose(run["a"], [1.0, 2.0])
        np.testing.assert_allclose(run["b/step"], [2.0])


class TestComparePlots:
    def test_png_written(self, run_dir, tmp_path):
        out = str(tmp_path / "curve.png")
        fig = compare_runs([run_dir], metric="avg_test_reward", smooth=3, out=out)
        assert os.path.exists(out) and os.path.getsize(out) > 0
        import matplotlib.pyplot as plt

        plt.close(fig)

    def test_time_axis_and_missing_metric(self, run_dir, tmp_path):
        out = str(tmp_path / "t.png")
        # one run missing the metric, one dir with no metrics.jsonl at all:
        # both skipped without raising; file still produced
        empty = tmp_path / "empty_run"
        empty.mkdir()
        fig = compare_runs([run_dir, str(empty)], metric="nope", x="t", out=out)
        assert os.path.exists(out)
        import matplotlib.pyplot as plt

        plt.close(fig)

    def test_label_mismatch_raises(self, run_dir):
        with pytest.raises(ValueError):
            compare_runs([run_dir, run_dir], labels=["only-one"])

    def test_cli(self, run_dir, tmp_path):
        from d4pg_tpu.utils.plotting import main

        out = str(tmp_path / "cli.png")
        main([run_dir, "--metric", "critic_loss", "--out", out, "--smooth", "0"])
        assert os.path.exists(out)
