"""Tests for on-device n-step return windows."""

import jax.numpy as jnp
import numpy as np

from d4pg_tpu.ops import nstep_returns


def oracle(rewards, dones, gamma, n):
    T = len(rewards)
    rets = np.zeros(T)
    boot = np.zeros(T)
    for t in range(T):
        g, alive = 0.0, True
        steps = 0
        for k in range(n):
            if t + k >= T or not alive:
                alive = False
                break
            g += gamma**k * rewards[t + k]
            steps += 1
            if dones[t + k]:
                alive = False
                break
        rets[t] = g
        boot[t] = (gamma**n) if (alive and steps == n) else 0.0
    return rets, boot


def test_nstep_matches_oracle():
    rng = np.random.default_rng(0)
    T = 64
    rewards = rng.normal(size=T)
    dones = (rng.uniform(size=T) < 0.15).astype(np.float64)
    for n in (1, 3, 5):
        got_r, got_b = nstep_returns(
            jnp.asarray(rewards, jnp.float32), jnp.asarray(dones, jnp.float32), 0.99, n
        )
        want_r, want_b = oracle(rewards, dones, 0.99, n)
        np.testing.assert_allclose(np.asarray(got_r), want_r, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_b), want_b, rtol=1e-5, atol=1e-6)


def test_one_step_reduces_to_rewards():
    rewards = jnp.asarray([1.0, 2.0, 3.0])
    dones = jnp.asarray([0.0, 0.0, 1.0])
    r, b = nstep_returns(rewards, dones, 0.9, 1)
    np.testing.assert_allclose(np.asarray(r), [1, 2, 3], atol=1e-6)
    np.testing.assert_allclose(np.asarray(b), [0.9, 0.9, 0.0], atol=1e-6)
