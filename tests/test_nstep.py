"""Tests for on-device n-step return windows."""

import jax.numpy as jnp
import numpy as np

from d4pg_tpu.ops import nstep_returns


def oracle(rewards, dones, gamma, n):
    T = len(rewards)
    rets, boot, offs = np.zeros(T), np.zeros(T), np.zeros(T, int)
    for t in range(T):
        g, m, terminated = 0.0, 0, False
        for k in range(n):
            if t + k >= T:
                break  # chunk boundary: stop, bootstrap still valid
            g += gamma**k * rewards[t + k]
            m += 1
            if dones[t + k]:
                terminated = True
                break
        rets[t] = g
        offs[t] = m
        boot[t] = 0.0 if terminated else gamma**m
    return rets, boot, offs


def test_nstep_matches_oracle():
    rng = np.random.default_rng(0)
    T = 64
    rewards = rng.normal(size=T)
    dones = (rng.uniform(size=T) < 0.15).astype(np.float64)
    for n in (1, 3, 5):
        got_r, got_b, got_m = nstep_returns(
            jnp.asarray(rewards, jnp.float32), jnp.asarray(dones, jnp.float32), 0.99, n
        )
        want_r, want_b, want_m = oracle(rewards, dones, 0.99, n)
        np.testing.assert_allclose(np.asarray(got_r), want_r, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_b), want_b, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(got_m), want_m)


def test_one_step_reduces_to_rewards():
    rewards = jnp.asarray([1.0, 2.0, 3.0])
    dones = jnp.asarray([0.0, 0.0, 1.0])
    r, b, m = nstep_returns(rewards, dones, 0.9, 1)
    np.testing.assert_allclose(np.asarray(r), [1, 2, 3], atol=1e-6)
    np.testing.assert_allclose(np.asarray(b), [0.9, 0.9, 0.0], atol=1e-6)
    np.testing.assert_array_equal(np.asarray(m), [1, 1, 1])


def test_chunk_boundary_keeps_bootstrap():
    # No dones: windows at the end of the chunk shrink but still bootstrap
    # with gamma^m (NOT treated as termination).
    rewards = jnp.ones(5)
    dones = jnp.zeros(5)
    r, b, m = nstep_returns(rewards, dones, 0.5, 3)
    np.testing.assert_array_equal(np.asarray(m), [3, 3, 3, 2, 1])
    np.testing.assert_allclose(
        np.asarray(b), [0.125, 0.125, 0.125, 0.25, 0.5], atol=1e-6
    )
    np.testing.assert_allclose(np.asarray(r[3]), 1 + 0.5, atol=1e-6)
