"""Tier-1-safe fleet-ingest microbench smoke.

Keeps the PR-7 ingest perf surface (in-process vs localhost-socket
windows/s, offered-rate shed engagement) exercised every test pass, and
pins the committed artifact's schema — the committed numbers live at
``benchmarks/ingest_microbench.json`` (regenerate with
``python benchmarks/ingest_microbench.py``)."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from ingest_microbench import run_microbench  # noqa: E402


def test_microbench_runs_and_records(tmp_path):
    out_path = str(tmp_path / "ingest_microbench.json")
    out = run_microbench(
        out_path,
        shapes=((5, 2),),
        frame_windows=16,
        duration_s=0.3,
        repeats=1,
        shed_rates=(20, 300),
        shed_duration_s=0.4,
        writers=2,
    )
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "ingest_microbench"
    shape = out["shapes"]["obs5_act2"]
    for path in ("inprocess", "fleet"):
        assert shape[path]["windows_per_sec"] > 0
        assert np.isfinite(shape[path]["windows_per_sec"])
    assert shape["fleet"]["mb_per_sec"] > 0
    assert shape["row_bytes"] == 4 * (2 * 5 + 2 + 2)
    # shed sweep: per-level accounting and the engagement point, with the
    # sub-saturation level clean and the past-capacity level shedding
    # (stub capacity 5k windows/s; 300 frames/s * 16 = 4800... keep the
    # high rate clearly past it via the offered_windows assertion instead)
    levels = out["shed"]["levels"]
    assert [lv["offered_frames_per_sec"] for lv in levels] == [20, 300]
    for lv in levels:
        assert 0.0 <= lv["shed_rate"] <= 1.0
        assert lv["windows_offered"] >= lv["windows_accepted"]
    assert levels[0]["shed_rate"] == 0.0  # far below capacity: no shed
    # multi-writer scale-out row: disjoint stacks, aggregate = sum
    mw = out["multi_writer"]
    assert mw["writers"] == 2
    assert mw["writers_1_windows_per_sec"] > 0
    assert mw["writers_2_aggregate_windows_per_sec"] == sum(
        mw["per_writer_windows_per_sec"]
    )
    assert "isolated-stack-sum" in mw["methodology"]


def test_committed_artifact_schema():
    path = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks",
        "ingest_microbench.json",
    )
    with open(path) as f:
        doc = json.load(f)
    assert doc["metric"] == "ingest_microbench"
    assert "obs17_act6" in doc["shapes"]  # the flagship shape is committed
    for shape in doc["shapes"].values():
        assert shape["inprocess"]["windows_per_sec"] > 0
        assert shape["fleet"]["windows_per_sec"] > 0
        assert shape["fleet"]["mb_per_sec"] > 0
        assert len(shape["fleet_repeats"]) == doc["repeats"]
        assert len(shape["inprocess_repeats"]) == doc["repeats"]
    shed = doc["shed"]
    assert shed["consumer_capacity_windows_per_sec"] > 0
    rates = [lv["shed_rate"] for lv in shed["levels"]]
    # the committed sweep crosses saturation: clean low end, engaged high
    assert rates[0] == 0.0 and rates[-1] > 0.0
    assert shed["shed_engagement_windows_per_sec"] is not None
    # the committed 2-writer aggregate row scales out (> 1x of one writer)
    mw = doc["multi_writer"]
    assert mw["writers"] == 2
    assert mw["scaling_x"] > 1.0
    assert mw["writers_2_aggregate_windows_per_sec"] > \
        mw["writers_1_windows_per_sec"]
