"""The healthz-driven autoscaler (`serve/autoscaler.py`): hysteresis,
cooldown, never-scale-on-one-sample, floor/ceiling clamps, the forced
chaos scale-down, and the signal adapters' gauge→signal mapping.

Everything here drives :meth:`Autoscaler.tick` directly (no timer
thread) so decisions are deterministic; the end-to-end
subprocess-spawning path runs in chaos_soak.sh leg 7.
"""

import time

import pytest

from d4pg_tpu.serve.autoscaler import (
    Autoscaler,
    IngestSignalSource,
    ScaleSignal,
    ServingSignalSource,
)


class _Pool:
    """Scripted actuators: counts calls, moves a replica gauge."""

    def __init__(self, replicas=1):
        self.replicas = replicas
        self.ups = 0
        self.downs = 0

    def up(self):
        self.ups += 1
        self.replicas += 1
        return True

    def down(self):
        self.downs += 1
        self.replicas -= 1
        return True


def _scaler(pool, loads, **kw):
    it = iter(loads)

    def signal():
        item = next(it)
        if isinstance(item, ScaleSignal):
            return item
        return ScaleSignal(load=item, replicas=pool.replicas)

    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("samples", 3)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("up_load", 0.8)
    kw.setdefault("down_load", 0.3)
    return Autoscaler(signal, pool.up, pool.down, **kw)


def test_never_scales_on_one_sample():
    pool = _Pool()
    s = _scaler(pool, [0.95, 0.1, 0.95, 0.1, 0.95, 0.1])
    for _ in range(6):
        s.tick()
    assert pool.ups == 0 and pool.downs == 0
    # alternating breaches reset both streaks: no action ever fires


def test_scales_up_after_k_consecutive_breaches():
    pool = _Pool()
    s = _scaler(pool, [0.9, 0.9, 0.9, 0.9])
    assert [s.tick() for _ in range(4)] == [None, None, "up", None]
    assert pool.ups == 1 and pool.replicas == 2
    # the streak reset after acting: one more breach is not enough again


def test_hysteresis_band_holds():
    """Load between down_load and up_load: no action in either
    direction, ever."""
    pool = _Pool(replicas=2)
    s = _scaler(pool, [0.5] * 10)
    for _ in range(10):
        assert s.tick() is None
    assert pool.ups == 0 and pool.downs == 0


def test_scales_down_after_k_quiet_samples_respecting_floor():
    pool = _Pool(replicas=3)
    s = _scaler(pool, [0.1] * 10, min_replicas=2)
    acts = [s.tick() for _ in range(10)]
    assert acts.count("down") == 1  # 3 -> 2, then pinned at the floor
    assert pool.replicas == 2 and pool.downs == 1


def test_ceiling_clamps_scale_up():
    pool = _Pool(replicas=4)
    s = _scaler(pool, [0.95] * 6, max_replicas=4)
    for _ in range(6):
        assert s.tick() is None
    assert pool.ups == 0


def test_cooldown_blocks_consecutive_actions():
    pool = _Pool()
    s = _scaler(pool, [0.9] * 20, cooldown_s=30.0, max_replicas=8)
    acts = [s.tick() for _ in range(12)]
    assert acts.count("up") == 1  # the second action sits out the cooldown
    # expire the cooldown: the loop may act again
    with s._lock:
        s._last_action_t = time.monotonic() - 60.0
    acts = [s.tick() for _ in range(3)]
    assert acts.count("up") == 1


def test_p99_slo_violation_breaches_even_at_low_load():
    pool = _Pool()
    sig = [ScaleSignal(load=0.2, p99_ms=500.0, replicas=1)] * 3
    s = _scaler(pool, sig, p99_slo_ms=100.0)
    assert [s.tick() for _ in range(3)] == [None, None, "up"]


def test_shed_rate_breaches_toward_scale_up():
    pool = _Pool()
    sig = [ScaleSignal(load=0.2, shed_rate=0.2, replicas=1)] * 3
    s = _scaler(pool, sig, shed_threshold=0.05)
    assert [s.tick() for _ in range(3)] == [None, None, "up"]


def test_signal_error_is_a_noop_sample_not_a_crash():
    pool = _Pool()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] % 2:
            raise OSError("probe refused")
        return ScaleSignal(load=0.9, replicas=pool.replicas)

    s = Autoscaler(flaky, pool.up, pool.down, samples=2, cooldown_s=0.0)
    acts = [s.tick() for _ in range(6)]
    assert s.signal_errors == 3
    # errored samples don't extend streaks; the 2 good breaches still act
    assert acts.count("up") >= 1


def test_chaos_forced_scaledown_bypasses_streaks_but_not_floor():
    from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

    inj = ChaosInjector(ChaosPlan.parse("scaledown_during_canary@2"))
    pool = _Pool(replicas=3)
    s = _scaler(pool, [0.5] * 6, chaos=inj, min_replicas=2)
    assert s.tick() is None          # tick 1: no fault, mid-band holds
    assert s.tick() == "down"        # tick 2: forced, no streak needed
    assert pool.downs == 1 and pool.replicas == 2
    assert inj.injections_total == 1
    # at the floor a forced scale-down is REFUSED: chaos must not be able
    # to scale the fleet to zero
    inj2 = ChaosInjector(ChaosPlan.parse("scaledown_during_canary@1"))
    s2 = _scaler(pool, [0.5] * 2, chaos=inj2, min_replicas=2)
    assert s2.tick() is None
    assert pool.downs == 1


def test_validation():
    pool = _Pool()
    with pytest.raises(ValueError, match="hysteresis"):
        _scaler(pool, [], up_load=0.3, down_load=0.5)
    with pytest.raises(ValueError, match="min_replicas"):
        _scaler(pool, [], min_replicas=3, max_replicas=2)


def test_control_thread_lifecycle():
    pool = _Pool()
    s = _scaler(pool, [0.5] * 1000, interval_s=0.01)
    s.start()
    time.sleep(0.1)
    s.close(timeout=5)
    assert s._thread is None and s.ticks >= 1
    snap = s.snapshot()
    assert snap["ticks"] == s.ticks and snap["scale_ups"] == 0


# ----------------------------------------------------- signal adapters
def test_serving_signal_maps_router_healthz():
    rows = iter([
        {"admitted": 2, "inflight": 8,
         "capacity": {"total": 16},
         "requests_total": 100, "replies_overloaded": 0,
         "interactive": {"p99_ms": 12.0}, "p99_ms": 50.0},
        {"admitted": 2, "inflight": 15,
         "capacity": {"total": 16},
         "requests_total": 200, "replies_overloaded": 20,
         "interactive": {"p99_ms": 80.0}, "p99_ms": 90.0},
    ])
    src = ServingSignalSource(lambda: next(rows))
    s1 = src()
    assert s1.load == pytest.approx(0.5) and s1.replicas == 2
    assert s1.p99_ms == 12.0  # the INTERACTIVE tier's p99, not aggregate
    s2 = src()
    # shed rate is the DELTA since the last sample: 20 sheds / 100 new reqs
    assert s2.shed_rate == pytest.approx(0.2)
    assert s2.load == pytest.approx(15 / 16)


def test_serving_signal_without_capacity_model_falls_back():
    src = ServingSignalSource(lambda: {
        "admitted": 2, "inflight": 3, "capacity": {"total": 0},
        "requests_total": 1, "replies_overloaded": 0,
    })
    assert src().load == pytest.approx(1.5)


def test_ingest_signal_starved_scales_up_shedding_scales_down(monkeypatch):
    t = {"now": 100.0}
    monkeypatch.setattr(time, "monotonic", lambda: t["now"])
    rows = iter([
        {"windows_ingested": 0, "windows_shed": 0, "connections": 1},
        # 10 s later: only 20 windows/s against a 100 w/s target — starved
        {"windows_ingested": 200, "windows_shed": 0, "connections": 1},
        # later: the learner sheds most of what arrives — overprovisioned
        {"windows_ingested": 210, "windows_shed": 500, "connections": 4},
    ])
    src = IngestSignalSource(lambda: next(rows), target_windows_per_s=100.0)
    first = src()
    assert first.load == 1.0  # no rate yet: hold
    t["now"] += 10.0
    starved = src()
    assert starved.load == pytest.approx(5.0)  # 100 target / 20 observed
    t["now"] += 10.0
    shedding = src()
    assert shedding.load == 0.0 and shedding.shed_rate > 0.9
    assert shedding.replicas == 4
    with pytest.raises(ValueError):
        IngestSignalSource(lambda: {}, target_windows_per_s=0)
