"""Tests for pure-JAX envs, on-device rollout, and the gymnasium adapter."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.envs import Pendulum, PointMassGoal, rollout
from d4pg_tpu.envs.gym_adapter import NormalizeAction


def test_pendulum_reset_and_step():
    env = Pendulum()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (3,)
    state, obs, r, term, trunc = env.step(state, jnp.asarray([0.5]))
    assert float(r) <= 0.0
    assert float(term) == 0.0
    # cos^2 + sin^2 == 1
    assert float(obs[0] ** 2 + obs[1] ** 2) == pytest.approx(1.0, abs=1e-5)


def test_pendulum_truncates_at_limit():
    env = Pendulum()
    state, obs = env.reset(jax.random.PRNGKey(0))
    for _ in range(env.max_episode_steps):
        state, obs, r, term, trunc = env.step(state, jnp.asarray([0.0]))
    assert float(trunc) == 1.0


def test_pendulum_matches_gym_dynamics():
    gymnasium = pytest.importorskip("gymnasium")
    genv = gymnasium.make("Pendulum-v1").unwrapped
    genv.reset(seed=0)
    theta, thetadot = 0.3, -0.5
    genv.state = np.array([theta, thetadot])
    from d4pg_tpu.envs.api import EnvState

    jenv = Pendulum()
    jstate = EnvState(
        physics=jnp.asarray([theta, thetadot]),
        t=jnp.zeros((), jnp.int32),
        key=jax.random.PRNGKey(0),
    )
    # torque 1.0 == canonical action 0.5 (max_torque 2)
    gobs, grew, *_ = genv.step(np.array([1.0]))
    jstate, jobs, jrew, *_ = jenv.step(jstate, jnp.asarray([0.5]))
    np.testing.assert_allclose(np.asarray(jobs), gobs, rtol=1e-5, atol=1e-5)
    assert float(jrew) == pytest.approx(float(grew), abs=1e-5)


def test_pointmass_goal_success_and_reward():
    env = PointMassGoal()
    state, obs = env.reset(jax.random.PRNGKey(1))
    assert obs.shape == (6,)
    r_far = env.compute_reward(jnp.asarray([0.0, 0.0]), jnp.asarray([1.0, 1.0]))
    r_near = env.compute_reward(jnp.asarray([0.0, 0.0]), jnp.asarray([0.01, 0.0]))
    assert float(r_far) == -1.0
    assert float(r_near) == 0.0


def test_rollout_scan_shapes_and_autoreset():
    env = PointMassGoal()
    env.max_episode_steps = 10

    def policy(obs, key):
        return jax.random.uniform(key, (2,), minval=-1, maxval=1)

    final_state, final_obs, traj = rollout(env, policy, jax.random.PRNGKey(0), 35)
    assert traj.obs.shape == (35, 6)
    assert traj.action.shape == (35, 2)
    # at least 3 truncations/terminations happened in 35 steps of <=10-step eps
    assert float(jnp.sum(jnp.maximum(traj.terminated, traj.truncated))) >= 3


def test_rollout_is_jittable_and_vmappable():
    env = Pendulum()

    def policy(obs, key):
        return jnp.tanh(obs[:1]) * 0.0

    f = jax.jit(lambda k: rollout(env, policy, k, 16)[2].reward)
    r = f(jax.random.PRNGKey(0))
    assert r.shape == (16,)
    batched = jax.vmap(lambda k: rollout(env, policy, k, 8)[2].reward)(
        jax.random.split(jax.random.PRNGKey(1), 4)
    )
    assert batched.shape == (4, 8)


def test_normalize_action_affine_roundtrip():
    n = NormalizeAction(low=np.array([-2.0, 0.0]), high=np.array([2.0, 10.0]))
    np.testing.assert_allclose(n.to_env(np.array([0.0, 0.0])), [0.0, 5.0])
    np.testing.assert_allclose(n.to_env(np.array([-1.0, 1.0])), [-2.0, 10.0])
    a = np.array([0.3, -0.7])
    np.testing.assert_allclose(n.to_canonical(n.to_env(a)), a, atol=1e-6)


def test_gym_adapter_pendulum():
    pytest.importorskip("gymnasium")
    from d4pg_tpu.envs import make_env

    env = make_env("Pendulum-v1")
    obs = env.reset(seed=0)
    assert obs.shape == (3,)
    assert env.action_dim == 1
    obs, r, term, trunc, info = env.step(np.array([0.5]))
    assert isinstance(r, float)
    env.close()


def test_gym_adapter_advertises_value_range():
    """ENV_VALUE_RANGES feeds _reconcile_config via the adapter's
    v_min/v_max attributes — gym ids in the table must not silently train
    on the Pendulum default support (round-4 fix: the table was dead)."""
    pytest.importorskip("gymnasium")
    pytest.importorskip("mujoco")
    from d4pg_tpu.envs.gym_adapter import ENV_VALUE_RANGES, GymAdapter

    # Hopper-v5: one of the ids that lives ONLY in ENV_VALUE_RANGES
    # (Pendulum-v1 moved to config.ENV_PRESETS, which reconcile checks
    # first — keeping it in both tables made this one a silent no-op,
    # ADVICE round-4).
    env = GymAdapter("Hopper-v5")
    assert (env.v_min, env.v_max) == ENV_VALUE_RANGES["Hopper-v5"]
    env.close()


def test_gym_adapter_no_value_range_outside_table():
    """ids outside ENV_VALUE_RANGES advertise nothing (reconcile keeps
    defaults). Separate from the positive case above: this one needs only
    gymnasium, not mujoco, and must keep running where mujoco is absent."""
    pytest.importorskip("gymnasium")
    from d4pg_tpu.envs.gym_adapter import GymAdapter

    env2 = GymAdapter("MountainCarContinuous-v0")
    assert not hasattr(env2, "v_min")
    env2.close()


def test_gymnasium_robotics_ids_register_lazily():
    """The reference's active loop is built around goal-dict robotics envs
    (main.py:144-148,161-184); their ids live in gymnasium_robotics, which
    registers only on import. The adapter must reach them without the caller
    importing anything (round-4 VERDICT missing #1: FetchReach-v4 raised
    NameNotFound with the package installed)."""
    pytest.importorskip("gymnasium")
    pytest.importorskip("gymnasium_robotics")
    from d4pg_tpu.envs.gym_adapter import GymAdapter

    env = GymAdapter("FetchReach-v4")
    assert env.is_goal_env and env.action_dim == 4
    assert env.observation_dim == 13  # 10 proprio + 3-dim desired goal
    obs = env.reset(seed=0)
    assert obs.shape == (13,)
    _, r, _, _, info = env.step(np.zeros(4, np.float32))
    assert "is_success" in info and r in (-1.0, 0.0)  # sparse reward
    g = env.last_goal_obs
    assert env.compute_reward(g["achieved_goal"], g["desired_goal"]) in (-1.0, 0.0)
    env.close()


def test_goal_env_success_terminates():
    """Reference convention (main.py:144-148): done comes from
    info['is_success'] for goal envs — the Fetch tasks themselves never
    terminate, and without success-cuts the sparse -1/0 value structure
    escapes the [-horizon, 0] support (round-5 fix: FetchReach sat at
    success 0.0 with unterminated successes). Drive the arm toward the goal
    with the ground-truth direction and assert the episode ends the step
    is_success first fires."""
    pytest.importorskip("gymnasium")
    pytest.importorskip("gymnasium_robotics")
    from d4pg_tpu.envs.gym_adapter import GymAdapter

    env = GymAdapter("FetchReach-v4")
    env.reset(seed=3)
    terminated = truncated = False
    success_seen = False
    for _ in range(50):
        g = env.last_goal_obs
        delta = np.asarray(g["desired_goal"]) - np.asarray(g["achieved_goal"])
        a = np.zeros(4, np.float32)
        # gripper action space is (dx, dy, dz, grip) scaled by the adapter
        a[:3] = np.clip(delta * 20.0, -1.0, 1.0)
        _, r, terminated, truncated, info = env.step(a)
        if info.get("is_success"):
            success_seen = True
            break
        assert not terminated  # must not cut before success
    env.close()
    assert success_seen, "greedy goal-seeking never succeeded; env changed?"
    assert terminated, "is_success must terminate the episode (ref main.py:144-148)"
