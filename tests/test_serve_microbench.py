"""Tier-1-safe serving microbench smoke.

Keeps the PR-3 serving perf surface (closed-loop batching ratio, open-loop
shed/latency per load level) exercised every test pass, and pins the
committed artifact's schema + its ≥5× batched-over-single acceptance
headline — the committed numbers live at
``benchmarks/serve_microbench.json`` (regenerate with
``JAX_PLATFORMS=cpu python benchmarks/serve_microbench.py``)."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from serve_microbench import run_microbench  # noqa: E402


def test_microbench_runs_and_records(tmp_path):
    out_path = str(tmp_path / "serve_microbench.json")
    out = run_microbench(
        out_path,
        hidden=8,
        max_batch=8,
        duration_s=0.4,
        closed_wide=(2, 8),
        overload_rates=(50, 400),
        repeats=1,
    )
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "serve_microbench"
    thr = out["throughput"]
    assert thr["single_rps"] > 0 and np.isfinite(thr["single_rps"])
    assert thr["saturated_rps"] >= thr["single_rps"] * 0.5  # sanity, not SLO
    assert thr["closed_loop"][0]["population"] == 1
    for level in thr["open_loop"]:
        assert level["shed_rate"] is not None
        assert level["achieved_rps"] >= 0
    # low-latency scenario: no window, single profile only
    assert out["low_latency"]["config"]["max_wait_us"] == 0
    assert out["low_latency"]["closed_loop"][0]["p50_ms"] > 0
    # overload scenario carries the stub label and per-level shed rates
    assert out["overload"]["config"]["infer_delay_ms"] > 0
    assert [lv["offered_rps"] for lv in out["overload"]["open_loop"]] == [50, 400]
    # compile-once-per-bucket: buckets for max_batch=8 are (1,2,4,8)
    assert thr["server"]["compile_count"] == 4


def test_committed_artifact_meets_acceptance():
    """The committed artifact must stay parseable, carry the per-level SLO
    surface, and show the ≥5× dynamic-batching headline plus engaged
    shedding at the top overload level."""
    path = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks", "serve_microbench.json"
    )
    with open(path) as f:
        art = json.load(f)
    assert art["metric"] == "serve_microbench"
    assert art["batched_over_single"] >= 5.0
    thr = art["throughput"]
    assert thr["single_rps"] > 0 and thr["saturated_rps"] > 0
    assert thr["server"]["compile_count"] >= 1
    for level in thr["open_loop"] + art["overload"]["open_loop"]:
        for k in ("offered_rps", "achieved_rps", "shed_rate", "p99_ms"):
            assert k in level
    # sub-saturation overload levels shed ~nothing; the top level sheds
    overload = art["overload"]["open_loop"]
    assert overload[0]["shed_rate"] <= 0.05
    assert overload[-1]["shed_rate"] > 0.1
    assert art["overload"]["config"]["infer_delay_ms"] > 0  # labeled stub