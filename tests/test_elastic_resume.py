"""Elastic-mesh resume (ISSUE 15 satellite): a checkpoint written on a
dp=8 mesh must restore onto a dp=4 mesh (and vice versa).

The contract is already implied by the save path — gather-whole via
``make_shard_and_gather_fns`` means Orbax serializes WHOLE logical
arrays, so the bytes are mesh-independent — and by ``--resume``
re-sharding through ``shard_fns`` built for the CURRENT mesh. The
device-PER priority sidecar makes the same claim via its HOST-SLOT-ORDER
layout (``striped_perm`` depends on dp, so the sidecar stores priorities
permuted back to host order and restore re-stripes them for whatever dp
is live). These tests pin both.
"""

import os

import jax
import numpy as np
import pytest

from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.config import TrainConfig, apply_env_preset
from d4pg_tpu.models.critic import DistConfig


def _cfg(log_dir: str, dp: int, **kw) -> TrainConfig:
    agent = D4PGConfig(hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11))
    base = dict(
        env="pendulum",
        num_envs=2,
        total_steps=4,
        warmup_steps=48,
        batch_size=16,          # divisible by 8 AND 4
        steps_per_dispatch=2,
        eval_interval=1000,
        eval_episodes=1,
        checkpoint_interval=4,
        replay_capacity=512,    # divisible by 8 AND 4
        prioritized=False,
        tree_backend="numpy",
        agent=agent,
        log_dir=log_dir,
        concurrent_eval=False,
        seed=3,
        replay_placement="device",
        dp=dp,
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


def _train_leg(cfg):
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(cfg)
    try:
        t.train()
        return int(jax.device_get(t.state.step))
    finally:
        t.close()


def test_dp8_checkpoint_resumes_on_dp4_mesh(tmp_path):
    """Save on the full 8-way virtual mesh, resume on a 4-way mesh: the
    gathered-whole checkpoint re-shards onto the smaller mesh and keeps
    training with flat budgets."""
    from d4pg_tpu.parallel.mesh import make_mesh  # noqa: F401 (mesh sanity)
    from d4pg_tpu.runtime.trainer import Trainer

    d = str(tmp_path / "run")
    step1 = _train_leg(_cfg(d, dp=8))
    t = Trainer(_cfg(d, dp=4, total_steps=8, resume=True,
                     debug_guards=True))
    try:
        assert t.grad_steps == step1
        leaf = jax.tree_util.tree_leaves(t.state.critic_params)[0]
        assert len(leaf.sharding.mesh.devices.flat) == 4
        t.train()
        assert t.sentinel.counts()["megastep"] == 1
        assert t._ledger.stats()["trips"] == 0
    finally:
        t.close()


@pytest.mark.slow
def test_dp4_checkpoint_resumes_on_dp8_mesh(tmp_path):
    """The scale-UP direction (a pod growing back) must work too."""
    from d4pg_tpu.runtime.trainer import Trainer

    d = str(tmp_path / "run")
    step1 = _train_leg(_cfg(d, dp=4))
    t = Trainer(_cfg(d, dp=8, total_steps=8, resume=True))
    try:
        assert t.grad_steps == step1
        leaf = jax.tree_util.tree_leaves(t.state.critic_params)[0]
        assert len(leaf.sharding.mesh.devices.flat) == 8
        t.train()
    finally:
        t.close()


@pytest.mark.slow
def test_dp8_checkpoint_resumes_single_device(tmp_path):
    """The degenerate shrink — a whole pod gone, one device left: the
    same gathered-whole bytes restore un-sharded."""
    from d4pg_tpu.runtime.trainer import Trainer

    d = str(tmp_path / "run")
    step1 = _train_leg(_cfg(d, dp=8))
    t = Trainer(_cfg(d, dp=None, total_steps=8, resume=True))
    try:
        assert t.grad_steps == step1
        t.train()
    finally:
        t.close()


def test_device_per_sidecar_resumes_across_dp(tmp_path):
    """Device-resident PER across a mesh shrink: the priority sidecar is
    stored in HOST slot order (striped_perm un-permutes the dp=8 lane
    layout), so a dp=4 resume must re-stripe the SAME per-slot
    priorities — pinned by comparing the restored tree's host-order
    leaves against the dp=8 snapshot."""
    from d4pg_tpu.runtime.trainer import Trainer

    d = str(tmp_path / "run")
    cfg8 = _cfg(d, dp=8, prioritized=True, snapshot_replay=True,
                total_steps=6)
    step1 = _train_leg(cfg8)
    snap = os.path.join(d, "checkpoints", "device_per.npz")
    assert os.path.exists(snap), "device-PER sidecar not written"
    with np.load(snap) as z:
        saved = z["priorities_alpha"].copy()
        saved_max = float(z["max_priority"])
    t = Trainer(_cfg(d, dp=4, prioritized=True, snapshot_replay=True,
                     total_steps=10, resume=True))
    try:
        assert t.grad_steps == step1
        pa, mp = t._dev_per.snapshot_host()
        np.testing.assert_allclose(pa, saved, rtol=1e-6)
        assert mp == pytest.approx(saved_max)
        t.train()  # keeps training on the restored priorities
    finally:
        t.close()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
