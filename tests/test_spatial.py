"""3D spatial physics engine: quantitative validation against MuJoCo on the
humanoid + the on-device Humanoid env built on it.

Same correctness bar as tests/test_planar.py: mass matrix, bias forces, and
FK must MATCH host MuJoCo compiled from the same MJCF (the f32 engine vs
f64 MuJoCo ⇒ f32-resolution tolerances). Contacts are penalty-based by
design (documented deviation) and validated behaviorally: the passive
humanoid falls and comes to rest at ground level without blowing up.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

mujoco = pytest.importorskip("mujoco")

from d4pg_tpu.envs.locomotion import Humanoid, _gym_xml
from d4pg_tpu.envs.spatial import (
    bias_force,
    body_coms,
    contact_points,
    extract_spatial_model,
    mass_matrix,
    step_physics,
)

XML = _gym_xml("humanoid.xml")


@pytest.fixture(scope="module")
def model():
    return extract_spatial_model(XML)


@pytest.fixture(scope="module")
def mj():
    m = mujoco.MjModel.from_xml_path(XML)
    return m, mujoco.MjData(m)


def _random_state(m, rng):
    """Random airborne pose: arbitrary root quaternion, joints inside their
    ranges, so only rigid-body terms are exercised (contacts inactive)."""
    q = np.array(m.qpos0)
    q[:2] = rng.uniform(-1, 1, 2)
    q[2] = 2.5
    quat = rng.normal(0, 1, 4)
    q[3:7] = quat / np.linalg.norm(quat)
    q[7:] += rng.uniform(-0.5, 0.5, m.nq - 7)
    v = rng.normal(0, 1.0, m.nv)
    return q, v


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_mass_matrix_matches_mujoco(model, mj):
    m, d = mj
    rng = np.random.default_rng(0)
    for _ in range(3):
        q, v = _random_state(m, rng)
        d.qpos[:], d.qvel[:] = q, v
        mujoco.mj_forward(m, d)
        M_mj = np.zeros((m.nv, m.nv))
        mujoco.mj_fullM(m, d, M_mj)
        M_ours = np.asarray(mass_matrix(model, jnp.asarray(q)))
        np.testing.assert_allclose(M_ours, M_mj, atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_bias_force_matches_mujoco_rne(model, mj):
    """Newton–Euler-through-autodiff == mj_rne(flg_acc=0): coriolis +
    centrifugal + gyroscopic + gravity, in MuJoCo's qvel conventions
    (world-frame linear, body-frame angular for the free joint)."""
    m, d = mj
    rng = np.random.default_rng(1)
    for _ in range(3):
        q, v = _random_state(m, rng)
        d.qpos[:], d.qvel[:] = q, v
        mujoco.mj_forward(m, d)
        bias_mj = np.zeros(m.nv)
        mujoco.mj_rne(m, d, 0, bias_mj)
        bias_ours = np.asarray(
            bias_force(model, jnp.asarray(q), jnp.asarray(v))
        )
        # bias components reach ~400 N at these velocities; f32 FK noise
        # accumulates through two jvps → absolute 2e-2 ≈ 5e-5 relative
        np.testing.assert_allclose(bias_ours, bias_mj, atol=2e-2, rtol=1e-3)


def test_fk_coms_match_mujoco(model, mj):
    m, d = mj
    rng = np.random.default_rng(2)
    q, v = _random_state(m, rng)
    d.qpos[:], d.qvel[:] = q, v
    mujoco.mj_forward(m, d)
    coms, _ = body_coms(model, jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(coms), d.xipos[1:], atol=1e-5)


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_passive_drop_stays_finite_and_settles(model):
    """Contact model check: the passive humanoid falls from the XML pose and
    comes to rest ON the ground (no sinking through, no explosion)."""

    @jax.jit
    def roll(q, v):
        def body(c, _):
            q, v = c
            q, v = step_physics(model, q, v, jnp.zeros(17), 10, 0.0015)
            return (q, v), None

        (q, v), _ = jax.lax.scan(body, (q, v), None, length=400)
        return q, v

    q0 = jnp.asarray(model.qpos0, jnp.float32)
    q, v = roll(q0, jnp.zeros(model.nv))
    assert bool(jnp.all(jnp.isfinite(q))) and bool(jnp.all(jnp.isfinite(v)))
    # fallen: torso z well below standing height but above the floor
    assert 0.05 < float(q[2]) < 1.0
    # at rest (velocities decayed)
    assert float(jnp.max(jnp.abs(v))) < 0.5
    # nothing sunk through the floor: worst penetration < 2 cm
    gaps = np.asarray(contact_points(model, q))[:, 2] - np.asarray(
        model.con_radius
    )
    assert gaps.min() > -0.02


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_ant_dynamics_match_mujoco():
    """Engine generality: ant.xml (free joint + 8 hinges, sphere + capsule
    geoms) extracts and matches MuJoCo with NO engine changes."""
    xml = _gym_xml("ant.xml")
    model = extract_spatial_model(xml)
    m = mujoco.MjModel.from_xml_path(xml)
    d = mujoco.MjData(m)
    rng = np.random.default_rng(4)
    for _ in range(2):
        q, v = _random_state(m, rng)
        d.qpos[:], d.qvel[:] = q, v
        mujoco.mj_forward(m, d)
        M_mj = np.zeros((m.nv, m.nv))
        mujoco.mj_fullM(m, d, M_mj)
        np.testing.assert_allclose(
            np.asarray(mass_matrix(model, jnp.asarray(q))), M_mj,
            atol=2e-4, rtol=2e-4,
        )
        bias_mj = np.zeros(m.nv)
        mujoco.mj_rne(m, d, 0, bias_mj)
        np.testing.assert_allclose(
            np.asarray(bias_force(model, jnp.asarray(q), jnp.asarray(v))),
            bias_mj, atol=2e-2, rtol=1e-3,
        )


class TestAntEnv:
    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_shapes_reward_and_termination(self):
        from d4pg_tpu.envs.locomotion import Ant

        env = Ant()
        state, obs = env.reset(jax.random.PRNGKey(0))
        assert obs.shape == (27,)
        step = jax.jit(env.step)
        state2, obs2, r, term, _ = step(state, jnp.zeros(8))
        # standing start, zero ctrl: reward ≈ healthy bonus (1.0)
        assert float(term) == 0.0 and 0.0 < float(r) < 2.0
        q, v = state.physics
        fallen = state._replace(physics=(q.at[2].set(0.05), v))
        _, _, _, term2, _ = step(fallen, jnp.zeros(8))
        assert float(term2) == 1.0

    def test_registry_and_preset(self):
        from d4pg_tpu.config import ENV_PRESETS, TrainConfig, apply_env_preset
        from d4pg_tpu.envs import make_env
        from d4pg_tpu.envs.locomotion import Ant

        assert isinstance(make_env("ant", None), Ant)
        cfg = apply_env_preset(TrainConfig(env="ant"))
        assert cfg.agent.obs_dim == 27 and cfg.agent.action_dim == 8

    def test_forward_reward_tracks_torso_not_model_com(self):
        """Ant-v5 tracks get_body_com("torso") for the forward reward;
        the whole-model mass-weighted COM is a different number whenever
        the legs are asymmetric (ADVICE round-3). Torso COM == its world
        x from forward kinematics; Humanoid keeps the model COM."""
        from d4pg_tpu.envs.locomotion import Ant
        from d4pg_tpu.envs.spatial import body_coms

        env = Ant()
        q = jnp.asarray(env.model.qpos0, jnp.float32)
        # asymmetric leg pose (one front leg folded): the model COM shifts
        # in x while the torso stays put
        q = q.at[7:15].set(
            jnp.array([0.9, 1.2, 0.0, 0.1, 0.0, -0.1, 0.0, 0.1])
        )
        torso_x = float(body_coms(env.model, q)[0][0, 0])
        assert abs(float(env._forward_x(q)) - torso_x) < 1e-6
        assert abs(float(env._com_x(q)) - torso_x) > 1e-3
        hum = Humanoid()
        qh = jnp.asarray(hum.model.qpos0, jnp.float32)
        assert abs(float(hum._forward_x(qh)) - float(hum._com_x(qh))) < 1e-9


class TestHumanoidEnv:
    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_reset_and_step_shapes_jit_vmap(self):
        env = Humanoid()
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        states, obs = jax.vmap(env.reset)(keys)
        assert obs.shape == (3, 45)
        actions = jnp.zeros((3, 17))
        states2, obs2, r, term, trunc = jax.vmap(env.step)(states, actions)
        assert obs2.shape == (3, 45) and r.shape == (3,)
        # starts healthy at the XML pose (z = 1.4) → no termination
        assert bool(jnp.all(term == 0.0))
        assert not np.allclose(np.asarray(obs[0]), np.asarray(obs[1]))

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_reward_healthy_bonus_and_termination(self):
        env = Humanoid()
        state, _ = env.reset(jax.random.PRNGKey(0))
        step = jax.jit(env.step)
        state2, _, r, term, _ = step(state, jnp.zeros(17))
        # standing start with zero ctrl: reward ≈ healthy bonus (5.0)
        assert float(term) == 0.0 and 3.0 < float(r) < 7.0
        # fallen root (z below 1.0) terminates
        q, v = state.physics
        fallen = state._replace(physics=(q.at[2].set(0.5), v))
        _, _, _, term2, _ = step(fallen, jnp.zeros(17))
        assert float(term2) == 1.0

    def test_obs_layout(self):
        env = Humanoid()
        state, obs = env.reset(jax.random.PRNGKey(3))
        q, v = state.physics
        np.testing.assert_allclose(np.asarray(obs[:22]), np.asarray(q[2:]))
        np.testing.assert_allclose(np.asarray(obs[22:]), np.asarray(v))
        # root quaternion stays unit under reset noise
        np.testing.assert_allclose(float(jnp.linalg.norm(q[3:7])), 1.0, atol=1e-6)

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_ctrl_scaled_by_ctrlrange(self):
        """Actions are canonical (−1,1); the MJCF ctrlrange is ±0.4, so the
        ctrl cost of a full-scale action is 0.1 · 17 · 0.4² = 0.272."""
        env = Humanoid()
        state, _ = env.reset(jax.random.PRNGKey(0))
        a = jnp.ones(17)
        state2, _, r, _, _ = jax.jit(env.step)(state, a)
        from d4pg_tpu.envs.spatial import body_coms as bc

        m = jnp.asarray(env.model.mass)
        com_x = lambda q: float(jnp.sum(m * bc(env.model, q)[0][:, 0]) / jnp.sum(m))
        x_vel = (com_x(state2.physics[0]) - com_x(state.physics[0])) / env.control_dt
        expect = 1.25 * x_vel - 0.1 * 17 * 0.16 + 5.0
        np.testing.assert_allclose(float(r), expect, rtol=1e-4)

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_nan_state_terminates_and_obs_stays_finite(self):
        """A physics blow-up (NaN/overspeed state) must read as a terminal
        step with finite obs/reward — one poisoned transition in the replay
        ring NaNs the learner within a few hundred grad steps (observed
        once in ~3M humanoid steps before this guard)."""
        env = Humanoid()
        state, _ = env.reset(jax.random.PRNGKey(0))
        q, v = state.physics
        bad = state._replace(physics=(q.at[3].set(jnp.nan), v))
        _, obs, r, term, _ = jax.jit(env.step)(bad, jnp.zeros(17))
        assert float(term) == 1.0
        assert bool(jnp.all(jnp.isfinite(obs))) and np.isfinite(float(r))
        assert float(r) == 0.0  # blown-up step: no reward, not just finite
        fast = state._replace(physics=(q, v.at[0].set(2e4)))
        _, obs2, r2, term2, _ = jax.jit(env.step)(fast, jnp.zeros(17))
        assert float(term2) == 1.0
        assert bool(jnp.all(jnp.isfinite(obs2))) and float(r2) == 0.0
        # sub-threshold divergence (finite=True, huge velocity): the reward
        # is bounded so the scalar critic can't be poisoned by a 1e4 spike
        near = state._replace(physics=(q, v.at[0].set(9e3)))
        _, _, r3, _, _ = jax.jit(env.step)(near, jnp.zeros(17))
        assert abs(float(r3)) <= 1e3

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_planar_envs_share_the_guard(self):
        """HalfCheetah's _is_healthy is constant-True — a NaN state must
        still terminate (and emit sanitized obs/reward), or the poisoned
        state survives auto-reset and NaNs the ring."""
        from d4pg_tpu.envs.locomotion import HalfCheetah

        env = HalfCheetah()
        state, _ = env.reset(jax.random.PRNGKey(0))
        q, qd = state.physics
        bad = state._replace(physics=(q.at[0].set(jnp.nan), qd))
        _, obs, r, term, _ = jax.jit(env.step)(bad, jnp.zeros(6))
        assert float(term) == 1.0
        assert bool(jnp.all(jnp.isfinite(obs))) and float(r) == 0.0

    def test_registry_and_preset(self):
        from d4pg_tpu.config import ENV_PRESETS, TrainConfig, apply_env_preset
        from d4pg_tpu.envs import make_env

        env = make_env("humanoid", None)
        assert isinstance(env, Humanoid)
        cfg = apply_env_preset(TrainConfig(env="humanoid"))
        assert cfg.agent.obs_dim == 45 and cfg.agent.action_dim == 17
        # 1500, not 1000: the round-4 v1500 study measured +15% from
        # widening past a saturated support (runs/humanoid_ondevice_v1500).
        assert ENV_PRESETS["humanoid"]["v_max"] == 1500.0
