"""Tier-1 router smoke: train → --export-bundle → two serve replicas →
router front-end → roundtrips → replica ``kill -9`` → failover →
graceful drains, through the real CLIs (``scripts/router_smoke.sh``), in
subprocesses with a clean CPU backend.

This is THE end-to-end smoke for the replicated-serving tier (conftest
fast-tier policy): everything else router-related tests in-process
(tests/test_router.py); only this one proves the shipped commands compose
across three real processes.
"""

import os
import subprocess
import sys

from conftest import clean_cpu_env


def test_router_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["ROUTER_SMOKE_DIR"] = str(tmp_path / "run")
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "router_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=840,
        env=env,
        cwd=repo,
    )
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "ROUTER_SMOKE_ROUNDTRIP_OK" in p.stdout, out[-4000:]
    assert "ROUTER_SMOKE_OK" in p.stdout, out[-4000:]


if __name__ == "__main__":
    sys.exit(0)
