"""Tier-1 flywheel smoke: the closed data loop through the real CLIs
(``scripts/flywheel_smoke.sh``) — a learner with NO local collection and
NO fleet actors paced to completion purely by MIRRORED serving traffic
(serve --mirror-fraction 1.0 + the sim client's FEEDBACK reward echo),
then a fixed-seed v1 evaluator run on the same server, a SIGTERM drain,
and the three-ledger audit: ingest per-source split, tap accounting
identity, gate-readable spool.

This is THE end-to-end smoke for the flywheel subsystem (conftest
fast-tier policy): everything else flywheel-related tests layers
in-process (``tests/test_flywheel.py``); only this one proves the
shipped commands compose. The promotion-gate leg (planted bad bundle
blocked, closed-loop improvement) needs real training time and lives in
``scripts/chaos_soak.sh`` leg 10.
"""

import os
import subprocess
import sys

from conftest import clean_cpu_env


def test_flywheel_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["FLYWHEEL_SMOKE_DIR"] = str(tmp_path / "run")
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "flywheel_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=840,
        env=env,
        cwd=repo,
    )
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "FLYWHEEL_SMOKE_COUNTERS_OK" in p.stdout, out[-4000:]
    assert "FLYWHEEL_SMOKE_OK" in p.stdout, out[-4000:]
    # the spool is a real on-disk artifact the gate could read
    spool = tmp_path / "run" / "spool"
    assert any(f.name.startswith("mirror-") for f in spool.iterdir())


if __name__ == "__main__":
    sys.exit(0)
