"""Sharded megastep: pjit partition-rule learner over a dp mesh (ROADMAP
item 2 — the scale-out of the PR-6 device-resident data plane).

The contracts under test, in dependency order:

1. the STRIPED sharded ring is a byte-exact mirror of the host buffer
   (lane d local row i == host slot i·D + d) through chunked ingest,
   uneven pending distributions and ring wrap, with exactly ONE ingest
   compile (budget 1, same as the unsharded sync);
2. BYTE-IDENTITY: the sharded megastep over the 8-way CPU virtual mesh
   produces a bit-exact TrainState vs the single-device parity oracle
   (the SAME ``sharded_megastep_uniform_body`` under ``vmap`` over
   striped lanes) — possible only because the body's sole cross-shard
   arithmetic is ``det_pmean``'s fixed-order sum; ``pmean``'s backend
   AllReduce would not replay;
3. the trainer's device placement composes with ``--dp``: state placed
   per the partition-rule registry, guards clean under ``--debug-guards``
   with the tightened zero-transfer budget, recompile budgets flat
   (megastep=1, ring_ingest=1), and checkpoints round-trip — gathered
   whole on save, RE-SHARDED onto the mesh on ``--resume`` (the
   ``make_shard_and_gather_fns`` port), including after ``kill -9``;
4. validation: the new flag surface fails loudly on unsupported
   combinations (hybrid+dp, tp>1, indivisible batch/capacity).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from d4pg_tpu.agent import D4PGConfig, create_train_state  # noqa: E402
from d4pg_tpu.config import TrainConfig, apply_env_preset  # noqa: E402
from d4pg_tpu.models.critic import DistConfig  # noqa: E402
from d4pg_tpu.parallel import make_mesh, shard_train_state  # noqa: E402
from d4pg_tpu.replay.device_ring import (  # noqa: E402
    ShardedDeviceRingSync,
    device_ring_init,
    striped_lanes,
    striped_perm,
)
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition  # noqa: E402
from d4pg_tpu.runtime.megastep import (  # noqa: E402
    make_megastep_uniform_oracle,
    make_megastep_uniform_sharded,
)


def _small_cfg(**kw) -> D4PGConfig:
    base = dict(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(16, 16),
        dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0),
    )
    base.update(kw)
    return D4PGConfig(**base)


def _fill(buf, n, seed=0):
    r = np.random.default_rng(seed)
    obs_dim = buf.obs.shape[1]
    act_dim = buf.action.shape[1]
    buf.add_batch(
        Transition(
            r.normal(size=(n, obs_dim)).astype(np.float32),
            r.uniform(-1, 1, (n, act_dim)).astype(np.float32),
            r.uniform(-1, 0, n).astype(np.float32),
            r.normal(size=(n, obs_dim)).astype(np.float32),
            np.full(n, 0.99, np.float32),
        )
    )


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb)
    )


# ------------------------------------------------------ striped ring mirror
class TestShardedRingMirror:
    def test_striped_mirror_matches_host_slots(self):
        D, C = 4, 64
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 41)  # uneven: shards own 11/10/10/10 filled rows
        ring = device_ring_init(C, 3, 1, mesh=mesh)
        sync = ShardedDeviceRingSync(buf, mesh, chunk_cap=16)
        ring = sync.flush(ring)
        assert int(ring.size) == 41
        perm = striped_perm(C, D)  # [D, C/D] host slots in device order
        for field in ("obs", "action", "reward", "next_obs", "discount"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ring, field)),
                getattr(buf, field)[perm].reshape(
                    (C,) + getattr(buf, field).shape[1:]
                ),
            )

    def test_mirror_through_ring_wrap(self):
        D, C = 4, 32
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        ring = device_ring_init(C, 3, 1, mesh=mesh)
        sync = ShardedDeviceRingSync(buf, mesh, chunk_cap=16)
        _fill(buf, 20, seed=1)
        ring = sync.flush(ring)
        _fill(buf, 20, seed=2)  # wraps
        ring = sync.flush(ring)
        assert int(ring.size) == C
        perm = striped_perm(C, D)
        np.testing.assert_array_equal(
            np.asarray(ring.obs), buf.obs[perm].reshape(C, 3)
        )

    def test_single_ingest_compile_across_flushes(self):
        D, C = 4, 64
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        ring = device_ring_init(C, 3, 1, mesh=mesh)
        sync = ShardedDeviceRingSync(buf, mesh, chunk_cap=8)
        for seed in range(4):
            _fill(buf, 10, seed=seed)
            ring = sync.flush(ring)
        assert sync.ingest_fn._cache_size() == 1

    def test_rows_land_sharded_over_dp(self):
        D, C = 4, 32
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 16)
        ring = ShardedDeviceRingSync(buf, mesh).flush(
            device_ring_init(C, 3, 1, mesh=mesh)
        )
        assert ring.obs.sharding == NamedSharding(mesh, P("dp", None))
        local = {s.data.shape for s in ring.obs.addressable_shards}
        assert local == {(C // D, 3)}

    def test_capacity_not_divisible_raises(self):
        mesh = make_mesh(dp=4, tp=1)
        with pytest.raises(ValueError, match="divisible"):
            device_ring_init(30, 3, 1, mesh=mesh)
        with pytest.raises(ValueError, match="divisible"):
            ShardedDeviceRingSync(ReplayBuffer(30, 3, 1), mesh)


# ----------------------------------------------------- byte-exact parity
class TestShardedMegastepParity:
    def test_byte_identical_vs_single_device_oracle(self):
        """THE acceptance contract (ISSUE 9): seeded math of the sharded
        megastep over the 8-way CPU virtual mesh is byte-identical to the
        single-device oracle — the same per-shard body vmapped over
        striped lanes, combined by the same fixed-order det_pmean."""
        D, K, B, C = 8, 3, 16, 128
        cfg = _small_cfg()
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 96)
        ring = ShardedDeviceRingSync(buf, mesh, chunk_cap=64).flush(
            device_ring_init(C, 3, 1, mesh=mesh)
        )
        mega = make_megastep_uniform_sharded(cfg, K, B, mesh)
        oracle = make_megastep_uniform_oracle(cfg, K, B, D)
        st_m = shard_train_state(create_train_state(cfg, jax.random.PRNGKey(1)), mesh)
        st_o = create_train_state(cfg, jax.random.PRNGKey(1))
        key_m = jax.device_put(
            jax.random.PRNGKey(7), NamedSharding(mesh, P())
        )
        key_o = jax.random.PRNGKey(7)
        lanes = striped_lanes(buf, D)
        for _ in range(3):
            st_m, key_m, met_m = mega(st_m, ring, key_m)
            st_o, key_o, met_o = oracle(st_o, lanes, key_o)
        # the WHOLE TrainState: params, targets, both Adam moment sets
        assert _leaves_equal(st_m, st_o)
        assert np.asarray(met_m["critic_loss"]) == np.asarray(
            met_o["critic_loss"]
        )

    def test_parity_holds_with_critic_ensemble(self):
        """The capacity the sharding unlocks composes with it: an E-wide
        ensemble (stack replicated over the dp mesh per stack_axes_for)
        keeps the byte-identity — the per-step random subset draw comes
        from the TrainState key, identical under both harnesses."""
        D, K, B, C = 4, 2, 8, 64
        cfg = _small_cfg(critic_ensemble=4, ensemble_min_targets=2)
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 48)
        ring = ShardedDeviceRingSync(buf, mesh).flush(
            device_ring_init(C, 3, 1, mesh=mesh)
        )
        mega = make_megastep_uniform_sharded(cfg, K, B, mesh)
        oracle = make_megastep_uniform_oracle(cfg, K, B, D)
        st_m = shard_train_state(create_train_state(cfg, jax.random.PRNGKey(2)), mesh)
        st_o = create_train_state(cfg, jax.random.PRNGKey(2))
        key_m = jax.device_put(jax.random.PRNGKey(9), NamedSharding(mesh, P()))
        key_o = jax.random.PRNGKey(9)
        lanes = striped_lanes(buf, D)
        for _ in range(2):
            st_m, key_m, _ = mega(st_m, ring, key_m)
            st_o, key_o, _ = oracle(st_o, lanes, key_o)
        assert _leaves_equal(st_m, st_o)

    def test_different_keys_diverge(self):
        """Sanity: the parity comparison is not vacuous."""
        D, K, B, C = 4, 2, 8, 64
        cfg = _small_cfg()
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 48)
        ring = ShardedDeviceRingSync(buf, mesh).flush(
            device_ring_init(C, 3, 1, mesh=mesh)
        )
        mega = make_megastep_uniform_sharded(cfg, K, B, mesh)
        sharding = NamedSharding(mesh, P())
        s1, _, _ = mega(
            shard_train_state(create_train_state(cfg, jax.random.PRNGKey(1)), mesh),
            ring, jax.device_put(jax.random.PRNGKey(7), sharding),
        )
        s2, _, _ = mega(
            shard_train_state(create_train_state(cfg, jax.random.PRNGKey(1)), mesh),
            ring, jax.device_put(jax.random.PRNGKey(8), sharding),
        )
        assert not _leaves_equal(s1.actor_params, s2.actor_params)

    def test_zero_transfer_guard_clean_on_mesh(self):
        """The PR-6 zero-transfer budget survives scale-out: a steady-state
        sharded dispatch runs clean under no_transfers (state, ring, key
        all mesh-resident)."""
        from d4pg_tpu.analysis import no_transfers

        D, K, B, C = 4, 2, 8, 64
        cfg = _small_cfg()
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        _fill(buf, 48)
        ring = ShardedDeviceRingSync(buf, mesh).flush(
            device_ring_init(C, 3, 1, mesh=mesh)
        )
        mega = make_megastep_uniform_sharded(cfg, K, B, mesh)
        state = shard_train_state(create_train_state(cfg, jax.random.PRNGKey(0)), mesh)
        key = jax.device_put(jax.random.PRNGKey(1), NamedSharding(mesh, P()))
        state, key, _ = mega(state, ring, key)  # warmup compile (exempt)
        with no_transfers():
            state, key, _ = mega(state, ring, key)  # clean

    def test_mesh_validation(self):
        cfg = _small_cfg()
        with pytest.raises(ValueError, match="dp-only"):
            make_megastep_uniform_sharded(cfg, 2, 8, make_mesh(dp=4, tp=2))
        with pytest.raises(ValueError, match="divisible"):
            make_megastep_uniform_sharded(cfg, 2, 9, make_mesh(dp=4, tp=1))


# ------------------------------------------------- trainer-level contracts
def _trainer_cfg(log_dir: str, **kw) -> TrainConfig:
    agent = kw.pop(
        "agent", D4PGConfig(hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11))
    )
    base = dict(
        env="pendulum",
        num_envs=2,
        total_steps=8,
        warmup_steps=48,
        batch_size=8,
        steps_per_dispatch=2,
        eval_interval=1000,
        eval_episodes=1,
        checkpoint_interval=100_000,
        replay_capacity=512,
        prioritized=False,
        tree_backend="numpy",
        agent=agent,
        log_dir=log_dir,
        concurrent_eval=False,
        seed=3,
        replay_placement="device",
        dp=4,
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


class TestTrainerShardedPlacement:
    def test_sharded_device_placement_guards_clean(self, tmp_path):
        """device placement + --dp under --debug-guards: zero-transfer
        steady state, recompile budgets flat (megastep=1, ring_ingest=1),
        zero leaked holds; state and ring land sharded per the rules."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_trainer_cfg(str(tmp_path / "dev"), debug_guards=True))
        try:
            t.train()
            assert t._megastep_warm
            counts = t.sentinel.counts()
            assert counts["megastep"] == 1
            assert counts["ring_ingest"] == 1
            assert t._ledger.stats()["active_holds"] == 0
            assert t._ledger.stats()["trips"] == 0
            assert t._ring.obs.sharding == NamedSharding(
                t._mega_mesh, P("dp", None)
            )
        finally:
            t.close()

    @pytest.mark.slow
    def test_checkpoint_roundtrip_reshards_on_mesh(self, tmp_path):
        """The make_shard_and_gather_fns port, end to end: leg 1 saves
        (leaves gathered WHOLE to host), leg 2 --resume re-shards onto the
        mesh per the rule registry and keeps training with flat budgets —
        no implicit reshard, no guard trip."""
        from d4pg_tpu.runtime.trainer import Trainer

        d = str(tmp_path / "run")
        t = Trainer(
            _trainer_cfg(d, total_steps=4, checkpoint_interval=4,
                         debug_guards=True)
        )
        try:
            t.train()
            step1 = int(jax.device_get(t.state.step))
        finally:
            t.close()
        t2 = Trainer(
            _trainer_cfg(d, total_steps=8, checkpoint_interval=4,
                         debug_guards=True, resume=True)
        )
        try:
            assert t2.grad_steps == step1
            leaf = jax.tree_util.tree_leaves(t2.state.critic_params)[0]
            assert isinstance(leaf.sharding, NamedSharding)
            assert leaf.sharding.mesh == t2._mega_mesh
            t2.train()
            counts = t2.sentinel.counts()
            assert counts["megastep"] == 1
            assert counts["ring_ingest"] == 1
            assert t2._ledger.stats()["trips"] == 0
        finally:
            t2.close()

    @pytest.mark.slow
    def test_kill9_resume_on_mesh(self, tmp_path):
        """kill -9 mid-run, then --resume on the mesh: the crash-consistent
        restore (manifest-verified) composes with the NamedSharding
        re-shard — the regression test the ISSUE names."""
        import os
        import signal
        import subprocess
        import sys
        import time

        from tests.conftest import clean_cpu_env

        d = str(tmp_path / "run")
        env = clean_cpu_env(pythonpath_repo=True)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        code = (
            "import sys; sys.argv=['train.py','--env','pendulum',"
            "'--num-envs','2','--warmup','48','--bsize','8',"
            "'--total-steps','4000','--steps-per-dispatch','2',"
            "'--eval-interval','1000','--eval-episodes','1',"
            "'--checkpoint-interval','4','--rmsize','512',"
            "'--no-p-replay','--tree-backend','numpy',"
            "'--hidden-sizes','16,16','--n-atoms','11',"
            "'--replay-placement','device','--dp','4',"
            f"'--log-dir',{d!r},'--no-concurrent-eval'];"
            "import train; train.main()"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        # Wait for at least one committed checkpoint, then SIGKILL.
        ckpt_dir = os.path.join(d, "checkpoints")
        deadline = time.monotonic() + 300
        committed = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"trainer exited early rc={proc.returncode}:\n{out}")
            if os.path.isdir(ckpt_dir) and any(
                n.startswith("manifest_") for n in os.listdir(ckpt_dir)
            ):
                committed = True
                break
            time.sleep(0.25)
        assert committed, "no committed checkpoint within deadline"
        proc.kill()  # SIGKILL: no cleanup, the crash the manifest attests
        proc.wait()
        # Resume on the same mesh, short leg, guards on.
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _trainer_cfg(d, total_steps=4, debug_guards=True, resume=True)
        )
        try:
            assert t.grad_steps >= 4  # restored an attested step
            t.train(4)  # one more short leg on the restored state
            assert t.sentinel.counts()["megastep"] == 1
            assert t._ledger.stats()["trips"] == 0
        finally:
            t.close()

    def test_placement_validation(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        with pytest.raises(ValueError, match="single-device"):
            Trainer(
                _trainer_cfg(
                    str(tmp_path / "a"), replay_placement="hybrid",
                    prioritized=True,
                )
            )
        with pytest.raises(ValueError, match="dp-only|tp"):
            Trainer(_trainer_cfg(str(tmp_path / "b"), tp=2))
        with pytest.raises(ValueError, match="divisible"):
            Trainer(_trainer_cfg(str(tmp_path / "c"), batch_size=10))
        with pytest.raises(ValueError, match="divisible"):
            Trainer(_trainer_cfg(str(tmp_path / "d"), replay_capacity=510))
        with pytest.raises(ValueError, match="host-path DP mode"):
            Trainer(_trainer_cfg(str(tmp_path / "e"), dp_hogwild=True))
