"""Tier-1 smokes for the replica-front-end microbench.

Two halves, mirroring the other benchmark smokes:

- the GENERATOR runs end-to-end at a tiny shape (a refactor that breaks
  ``bench_serve_router``/``run_microbench`` fails here, not at
  artifact-regen time). The scaling RATIO is not asserted at this scale
  (CPU noise), but the accounting identity is — zero silent losses during
  the replica kill is a correctness contract, not a performance number;
- the COMMITTED artifact (``benchmarks/router_microbench.json``) keeps its
  schema and the acceptance headlines: ≥1.5× aggregate throughput at 2
  replicas, availability ≥0.99 through an abrupt replica kill with the
  identity holding exactly, and at least one ejection recorded (the kill
  was real). Regenerate: ``JAX_PLATFORMS=cpu python
  benchmarks/router_microbench.py``.
"""

from __future__ import annotations

import json
import os

import pytest

pytest.importorskip("jax")

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "router_microbench.json",
)


def test_generator_runs_at_small_shape(tmp_path):
    from benchmarks.router_microbench import run_microbench

    out_path = str(tmp_path / "router_microbench.json")
    out = run_microbench(
        out_path,
        hidden=8,
        max_batch=8,
        conns=2,
        window=4,
        duration_s=0.5,
        infer_delay_ms=20.0,
        repeats=1,
    )
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "router_microbench"
    assert len(out["scaling"]) == 2
    for row in out["scaling"]:
        assert row["throughput_rps"] > 0
        assert row["identity_ok"] is True and row["lost"] == 0
    avail = out["availability"]
    # the correctness half holds at ANY scale: the kill loses nothing
    assert avail["identity_ok"] is True and avail["lost"] == 0
    assert avail["ok"] + avail["overloaded"] + avail["error"] == avail["submitted"]
    assert avail["router_ejections"] >= 1
    assert out["ratio_repeats"] and out["scaling_2_over_1"] is not None


def test_committed_artifact_meets_acceptance():
    with open(ARTIFACT) as f:
        art = json.load(f)
    assert art["metric"] == "router_microbench"
    assert art["backend"] == "cpu"  # chip-independent artifact
    # scaling headline: a second replica buys real aggregate capacity
    assert art["scaling_2_over_1"] >= 1.5
    assert art["scaling"][0]["replicas"] == 1
    assert art["scaling"][1]["replicas"] == 2
    assert (
        art["scaling"][1]["throughput_rps"]
        > art["scaling"][0]["throughput_rps"]
    )
    # p99 must not blow up when the fleet doubles (same closed population)
    assert art["scaling"][1]["p99_ms"] <= art["scaling"][0]["p99_ms"] * 1.5
    # availability headline: a mid-stream replica kill costs at most 1% of
    # requests (bounded-retry failover) and NEVER accounting integrity
    avail = art["availability"]
    assert avail["identity_ok"] is True and avail["lost"] == 0
    assert avail["availability"] >= 0.99
    assert avail["router_ejections"] >= 1
    assert avail["router_retries"] >= 1
    # the slow-device stub must stay labeled (the scaling regime claim
    # depends on it — see the generator docstring)
    assert art["infer_delay_ms"] > 0
    assert art["config"]["infer_delay_ms"] > 0
    assert len(art["ratio_repeats"]) == art["repeats"]
