"""The shared Backoff policy: bounded attempts, monotonic deadline,
seeded jitter — the contract the d4pglint ``unbounded-retry`` check
points every retry loop at."""

import random

import pytest

from d4pg_tpu.utils.retry import Backoff, call_with_retry


def _backoff(**kw):
    base = dict(
        base_s=1.0, factor=2.0, max_s=100.0, max_attempts=4, jitter=0.0,
        rng=random.Random(0), sleep=lambda s: None,
    )
    base.update(kw)
    return Backoff(**base)


def test_exponential_growth_and_attempt_bound():
    b = _backoff()
    assert [b.next_delay() for _ in range(6)] == [1.0, 2.0, 4.0, 8.0, None, None]


def test_caps_at_max_s():
    b = _backoff(max_s=3.0, max_attempts=5)
    assert [b.next_delay() for _ in range(5)] == [1.0, 2.0, 3.0, 3.0, 3.0]


def test_reset_restarts_schedule():
    b = _backoff()
    assert b.next_delay() == 1.0 and b.next_delay() == 2.0
    b.reset()  # a success makes failures non-consecutive
    assert b.next_delay() == 1.0


def test_jitter_bounded_and_deterministic():
    delays_a = [
        _backoff(jitter=0.5, rng=random.Random(7)).next_delay()
        for _ in range(1)
    ]
    delays_b = [
        _backoff(jitter=0.5, rng=random.Random(7)).next_delay()
        for _ in range(1)
    ]
    assert delays_a == delays_b  # seeded rng → reproducible chaos runs
    for _ in range(50):
        d = _backoff(jitter=0.5, rng=random.Random()).next_delay()
        assert 0.5 <= d <= 1.5  # nominal 1.0 ± 50%


def test_monotonic_deadline_exhausts_budget():
    now = [0.0]
    b = _backoff(deadline_s=5.0, max_attempts=100, clock=lambda: now[0])
    assert b.next_delay() is not None
    now[0] = 5.0
    assert b.next_delay() is None


def test_iterator_sleeps_between_bounded_attempts():
    slept = []
    b = _backoff(max_attempts=3, sleep=slept.append)
    attempts = list(b)
    assert attempts == [0, 1, 2, 3]  # first attempt free + 3 retries
    assert slept == [1.0, 2.0, 4.0]


def test_call_with_retry_succeeds_midway_and_raises_at_exhaustion():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert call_with_retry(flaky, backoff=_backoff()) == "ok"
    assert len(calls) == 3

    retried = []
    with pytest.raises(OSError, match="persistent"):
        call_with_retry(
            lambda: (_ for _ in ()).throw(OSError("persistent")),
            backoff=_backoff(max_attempts=2),
            on_retry=lambda attempt, e: retried.append(attempt),
        )
    assert retried == [0, 1, 2]
