"""Pallas projection kernel vs the XLA reference implementation (interpret
mode on CPU; the real-TPU comparison runs in bench/verify).

The fused projection+loss kernel is held to the same oracle: values,
priority signals AND gradients must match ``categorical_projection`` +
``categorical_td_loss`` across n-step discounts, mixed-sign/one-sided
supports, edge atoms (rewards clipped at both support ends) and
non-tile-aligned batches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.ops import categorical_projection, categorical_td_loss, make_support
from d4pg_tpu.ops.pallas_projection import (
    categorical_projection_pallas,
    fused_categorical_loss,
)


@pytest.mark.parametrize("batch", [32, 128, 200])
def test_pallas_matches_xla(batch):
    rng = np.random.default_rng(0)
    support = make_support(-10.0, 10.0, 51)
    logits = rng.normal(size=(batch, 51))
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    rewards = rng.uniform(-15, 15, size=batch).astype(np.float32)
    discounts = rng.choice([0.0, 0.99**5, 0.95], size=batch).astype(np.float32)

    want = categorical_projection(
        support, jnp.asarray(probs, jnp.float32), jnp.asarray(rewards), jnp.asarray(discounts)
    )
    got = categorical_projection_pallas(
        support, jnp.asarray(probs, jnp.float32), jnp.asarray(rewards),
        jnp.asarray(discounts), True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got).sum(-1), 1.0, atol=1e-5)


def test_pallas_terminal_and_clip():
    support = make_support(-1.0, 1.0, 5)
    probs = jnp.ones((3, 5)) / 5.0
    out = categorical_projection_pallas(
        support, probs,
        jnp.asarray([100.0, -100.0, 0.0]),
        jnp.asarray([0.0, 0.0, 0.0]),
        True,
    )
    np.testing.assert_allclose(np.asarray(out[0]), [0, 0, 0, 0, 1], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), [1, 0, 0, 0, 0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[2]), [0, 0, 1, 0, 0], atol=1e-6)


def _random_case(rng, batch, atoms, v_min, v_max):
    logits = jnp.asarray(rng.normal(size=(batch, atoms)), jnp.float32)
    tlog = rng.normal(size=(batch, atoms))
    target_probs = jnp.asarray(
        np.exp(tlog) / np.exp(tlog).sum(-1, keepdims=True), jnp.float32
    )
    # Rewards deliberately overshoot BOTH support ends so the clip/edge-atom
    # branch (full mass onto atom 0 or A-1) is exercised every run.
    rewards = jnp.asarray(
        rng.uniform(v_min - abs(v_min), v_max + abs(v_max), size=batch), jnp.float32
    )
    # γⁿ spread: terminal (0), long n-step windows, and ~1 discounts.
    discounts = jnp.asarray(
        rng.choice([0.0, 0.99**5, 0.95, 0.98**3, 1.0], size=batch), jnp.float32
    )
    weights = jnp.asarray(rng.uniform(0.1, 1.0, size=batch), jnp.float32)
    return logits, target_probs, rewards, discounts, weights


@pytest.mark.parametrize(
    "batch,atoms,v_min,v_max",
    [
        (32, 51, -10.0, 10.0),    # mixed-sign support
        (128, 51, 0.0, 1000.0),   # one-sided positive (flagship HalfCheetah)
        (200, 21, -300.0, 0.0),   # one-sided negative, non-tile batch
        (7, 11, -1.0, 1.0),       # tiny batch ≪ tile
    ],
)
def test_fused_loss_matches_oracle(batch, atoms, v_min, v_max):
    rng = np.random.default_rng(3)
    support = make_support(v_min, v_max, atoms)
    logits, target_probs, rewards, discounts, weights = _random_case(
        rng, batch, atoms, v_min, v_max
    )

    proj = jax.lax.stop_gradient(
        categorical_projection(support, target_probs, rewards, discounts)
    )

    def oracle(q):
        loss, ce = categorical_td_loss(q, proj, weights)
        return loss, ce

    (o_loss, o_ce), o_grad = jax.value_and_grad(oracle, has_aux=True)(logits)
    o_overlap = jnp.abs(-jnp.sum(proj * jax.nn.softmax(logits, -1), -1))

    def fused(q):
        ce, ov = fused_categorical_loss(
            support, q, target_probs, rewards, discounts, interpret=True
        )
        return jnp.mean(weights * ce), (ce, ov)

    (f_loss, (f_ce, f_ov)), f_grad = jax.value_and_grad(fused, has_aux=True)(logits)

    np.testing.assert_allclose(float(f_loss), float(o_loss), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(f_ce), np.asarray(o_ce), atol=1e-5)
    np.testing.assert_allclose(np.asarray(f_ov), np.asarray(o_overlap), atol=1e-5)
    # The gradient is the roofline-critical half: the fused backward kernel
    # RECOMPUTES the projection in VMEM — it must equal the autodiff of the
    # materialized-oracle loss.
    np.testing.assert_allclose(np.asarray(f_grad), np.asarray(o_grad), atol=1e-6)


def test_fused_loss_terminal_edge_atoms():
    """discount 0 + out-of-range rewards: all target mass on an edge atom;
    CE must reduce to −log_softmax at that atom exactly."""
    support = make_support(-1.0, 1.0, 5)
    probs = jnp.ones((3, 5)) / 5.0
    logits = jnp.asarray(
        np.arange(15, dtype=np.float32).reshape(3, 5) / 5.0
    )
    rewards = jnp.asarray([100.0, -100.0, 0.0])
    discounts = jnp.zeros(3)
    ce, ov = fused_categorical_loss(
        support, logits, probs, rewards, discounts, interpret=True
    )
    logp = np.asarray(jax.nn.log_softmax(logits, -1))
    sm = np.asarray(jax.nn.softmax(logits, -1))
    for b, atom in [(0, 4), (1, 0), (2, 2)]:
        np.testing.assert_allclose(float(ce[b]), -logp[b, atom], atol=1e-6)
        np.testing.assert_allclose(float(ov[b]), sm[b, atom], atol=1e-6)


def test_fused_loss_overlap_gradient_matches_oracle():
    """The overlap output's VJP (a future overlap-based loss term must get
    the exact gradient, not a silently dropped cotangent): grad of
    mean(ov) through the fused kernel vs autodiff of the materialized
    oracle expression."""
    rng = np.random.default_rng(7)
    support = make_support(-10.0, 10.0, 31)
    logits, target_probs, rewards, discounts, _ = _random_case(
        rng, 48, 31, -10.0, 10.0
    )
    proj = jax.lax.stop_gradient(
        categorical_projection(support, target_probs, rewards, discounts)
    )

    def oracle(q):
        return jnp.mean(jnp.abs(-jnp.sum(proj * jax.nn.softmax(q, -1), -1)))

    def fused(q):
        _, ov = fused_categorical_loss(
            support, q, target_probs, rewards, discounts, interpret=True
        )
        return jnp.mean(ov)

    o_grad = jax.grad(oracle)(logits)
    f_grad = jax.grad(fused)(logits)
    np.testing.assert_allclose(np.asarray(f_grad), np.asarray(o_grad), atol=1e-6)


def test_fused_loss_under_vmap_matches_oracle():
    """Twin-critic shape: vmap over a stacked leading axis of predictions
    (the custom_vjp + pallas_call pair must batch correctly)."""
    rng = np.random.default_rng(11)
    support = make_support(-10.0, 10.0, 31)
    B, A = 40, 31
    logits2 = jnp.asarray(rng.normal(size=(2, B, A)), jnp.float32)
    _, target_probs, rewards, discounts, weights = _random_case(
        rng, B, A, -10.0, 10.0
    )
    proj = categorical_projection(support, target_probs, rewards, discounts)

    def fused_one(q):
        ce, _ = fused_categorical_loss(
            support, q, target_probs, rewards, discounts, interpret=True
        )
        return jnp.mean(weights * ce)

    def oracle_one(q):
        loss, _ = categorical_td_loss(q, jax.lax.stop_gradient(proj), weights)
        return loss

    f_losses, f_grads = jax.vmap(jax.value_and_grad(fused_one))(logits2)
    o_losses, o_grads = jax.vmap(jax.value_and_grad(oracle_one))(logits2)
    np.testing.assert_allclose(np.asarray(f_losses), np.asarray(o_losses), atol=1e-6)
    np.testing.assert_allclose(np.asarray(f_grads), np.asarray(o_grads), atol=1e-6)
