"""Pallas projection kernel vs the XLA reference implementation (interpret
mode on CPU; the real-TPU comparison runs in bench/verify)."""

import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.ops import categorical_projection, make_support
from d4pg_tpu.ops.pallas_projection import categorical_projection_pallas


@pytest.mark.parametrize("batch", [32, 128, 200])
def test_pallas_matches_xla(batch):
    rng = np.random.default_rng(0)
    support = make_support(-10.0, 10.0, 51)
    logits = rng.normal(size=(batch, 51))
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    rewards = rng.uniform(-15, 15, size=batch).astype(np.float32)
    discounts = rng.choice([0.0, 0.99**5, 0.95], size=batch).astype(np.float32)

    want = categorical_projection(
        support, jnp.asarray(probs, jnp.float32), jnp.asarray(rewards), jnp.asarray(discounts)
    )
    got = categorical_projection_pallas(
        support, jnp.asarray(probs, jnp.float32), jnp.asarray(rewards),
        jnp.asarray(discounts), True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got).sum(-1), 1.0, atol=1e-5)


def test_pallas_terminal_and_clip():
    support = make_support(-1.0, 1.0, 5)
    probs = jnp.ones((3, 5)) / 5.0
    out = categorical_projection_pallas(
        support, probs,
        jnp.asarray([100.0, -100.0, 0.0]),
        jnp.asarray([0.0, 0.0, 0.0]),
        True,
    )
    np.testing.assert_allclose(np.asarray(out[0]), [0, 0, 0, 0, 1], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), [1, 0, 0, 0, 0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[2]), [0, 0, 1, 0, 0], atol=1e-6)
