"""Device-resident PER (ISSUE 14 / ROADMAP item 2 of the current arc).

The contracts under test, in dependency order:

1. the device segment tree is structurally the host tree: same layout,
   same totals, same descent (identical index draws for identical
   prefixes on the f64 host trees, both backends), same duplicate
   (last-wins) write-back semantics, pad slots dropped;
2. FROZEN-LITERAL STREAM PARITY (the PR-6 discipline): the device draw's
   prefixes are reproducible on host from the same key, so over multiple
   dispatches the host sum-tree oracle descended with those exact
   prefixes yields IDENTICAL seeded index draws, f32-resolution-equal IS
   weights, and f32-close post-writeback priorities — pinned as frozen
   literals so the device stream can never silently shift, on BOTH host
   tree backends (numpy and native);
3. the Pallas blocked-prefix-scan descent (``ops/pallas_tree.py``,
   interpret mode on CPU) equals the XLA reference descent —
   the backend-ladder oracle contract;
4. SHARDED BIT-IDENTITY (the PR-9 discipline): the dp=8 mesh device-PER
   megastep produces a bit-exact TrainState AND priority tree vs the
   single-device vmap oracle over striped lanes — possible only because
   the body's cross-shard arithmetic is det_pmean plus exact
   order-independent min/max reduces;
5. the trainer's ``--replay-placement device`` now KEEPS prioritized
   replay (plain host ring + device tree, no downgrade), runs clean
   under ``--debug-guards`` with the tightened zero-transfer budget and
   flat compile budgets (megastep=1, ring_ingest=1, tree_ingest=1), and
   snapshots/restores the tree priorities across --resume.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from d4pg_tpu.agent import D4PGConfig, create_train_state  # noqa: E402
from d4pg_tpu.config import TrainConfig, apply_env_preset  # noqa: E402
from d4pg_tpu.models.critic import DistConfig  # noqa: E402
from d4pg_tpu.replay import device_per as dper  # noqa: E402
from d4pg_tpu.replay.per import PrioritizedReplayBuffer  # noqa: E402
from d4pg_tpu.replay.segment_tree import SumTree  # noqa: E402
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition  # noqa: E402

CAP, K, B, SIZE = 64, 3, 4, 48


def _per_buf(backend: str) -> PrioritizedReplayBuffer:
    """The seeded host buffer the frozen literals are pinned against
    (same recipe as test_megastep's hybrid determinism fixture)."""
    buf = PrioritizedReplayBuffer(CAP, 3, 2, tree_backend=backend)
    r = np.random.default_rng(5)
    buf.add_batch(
        Transition(
            r.normal(size=(SIZE, 3)).astype(np.float32),
            r.uniform(-1, 1, (SIZE, 2)).astype(np.float32),
            r.uniform(-1, 0, SIZE).astype(np.float32),
            r.normal(size=(SIZE, 3)).astype(np.float32),
            np.full(SIZE, 0.99, np.float32),
        )
    )
    buf.update_priorities(
        np.arange(SIZE), r.uniform(0.1, 3.0, SIZE).astype(np.float64)
    )
    return buf


def _tree_from_buf(buf) -> dper.DevicePerTree:
    """Seed a device tree with the host buffer's exact α'd leaves."""
    pa = np.zeros(CAP, np.float32)
    pa[:SIZE] = np.asarray(buf._sum.get(np.arange(SIZE)), np.float32)
    return dper.tree_from_priorities(
        pa, CAP, max_priority=float(buf._max_priority)
    )


# ------------------------------------------------------------ tree structure
class TestDeviceTreeStructure:
    def test_set_leaves_matches_host_tree(self):
        r = np.random.default_rng(0)
        pri = r.uniform(0.1, 3.0, CAP)
        ht = SumTree(CAP)
        ht.set(np.arange(CAP), pri)
        lane = dper.set_leaves(
            jnp.zeros(dper.tree_width(CAP), jnp.float32),
            jnp.arange(CAP, dtype=jnp.int32),
            jnp.asarray(pri, jnp.float32),
            CAP,
        )
        half = dper.tree_width(CAP) // 2
        np.testing.assert_allclose(
            np.asarray(lane[half: half + CAP]), pri.astype(np.float32),
            rtol=0,
        )
        assert abs(float(lane[1]) - ht.sum()) < 1e-4

    def test_descend_matches_host_tree_exactly(self):
        r = np.random.default_rng(1)
        pri = r.uniform(0.1, 3.0, CAP)
        ht = SumTree(CAP)
        ht.set(np.arange(CAP), pri)
        lane = dper.set_leaves(
            jnp.zeros(dper.tree_width(CAP), jnp.float32),
            jnp.arange(CAP, dtype=jnp.int32),
            jnp.asarray(pri, jnp.float32),
            CAP,
        )
        pre = r.uniform(0.0, float(lane[1]) * (1 - 1e-6), 256)
        idx_d = dper.descend_prefix(lane, jnp.asarray(pre, jnp.float32))
        idx_h = ht.find_prefixsum_idx(pre)
        np.testing.assert_array_equal(np.asarray(idx_d), idx_h)

    def test_descend_skips_zero_mass_leaves(self):
        """The >= boundary semantics: a prefix landing exactly on a
        cumsum boundary selects the NEXT nonzero leaf (host contract)."""
        pri = np.array([2.0, 0.0, 3.0, 0.0], np.float64)
        lane = dper.set_leaves(
            jnp.zeros(8, jnp.float32), jnp.arange(4, dtype=jnp.int32),
            jnp.asarray(pri, jnp.float32), 4,
        )
        idx = dper.descend_prefix(
            lane, jnp.asarray([0.0, 1.9, 2.0, 4.9], jnp.float32)
        )
        assert np.asarray(idx).tolist() == [0, 0, 2, 2]

    def test_update_duplicates_last_wins(self):
        """The host trees' numpy-assignment duplicate semantics, made
        deterministic on device via the scatter-max winner pick."""
        lane = dper.set_leaves(
            jnp.zeros(dper.tree_width(CAP), jnp.float32),
            jnp.arange(CAP, dtype=jnp.int32),
            jnp.ones(CAP, jnp.float32),
            CAP,
        )
        lane = dper.update_leaves_last_wins(
            lane,
            jnp.asarray([3, 5, 3, 7, 3], jnp.int32),
            jnp.asarray([9.0, 2.0, 4.0, 6.0, 1.5], jnp.float32),
            CAP,
        )
        half = dper.tree_width(CAP) // 2
        assert float(lane[half + 3]) == 1.5   # last write
        assert float(lane[half + 5]) == 2.0
        assert float(lane[half + 7]) == 6.0
        ht = SumTree(CAP)
        ht.set(np.arange(CAP), np.ones(CAP))
        ht.set(np.array([3, 5, 3, 7, 3]), np.array([9.0, 2.0, 4.0, 6.0, 1.5]))
        assert abs(float(lane[1]) - ht.sum()) < 1e-4

    def test_pad_slots_are_dropped(self):
        """Ring-ingest pad slots (value == capacity) must not seed
        phantom mass — not even into the pow2 padding leaves."""
        lane = jnp.zeros(dper.tree_width(48), jnp.float32)  # 48 < L=64
        lane2 = dper.tree_ingest_lane_body(
            0.6, 48, lane, jnp.float32(1.0),
            jnp.full(16, 48, jnp.int32),  # all pads
        )
        assert float(jnp.abs(lane2).sum()) == 0.0

    def test_snapshot_restore_roundtrip_striped(self):
        r = np.random.default_rng(3)
        pa = r.uniform(0.1, 2.0, CAP).astype(np.float32)
        for shards in (1, 4):
            sync = dper.DevicePerSync.__new__(dper.DevicePerSync)
            sync.capacity, sync.alpha = CAP, 0.6
            sync._mesh, sync.n_shards = None, shards
            sync.local_capacity = CAP // shards
            sync.restore_host(pa, 2.5)
            got, mp = sync.snapshot_host()
            np.testing.assert_array_equal(got, pa)
            assert mp == 2.5


# ------------------------------------- frozen-literal host-tree stream parity
# The determinism contract, frozen: PRNGKey(7) split once, fold_in(0),
# over the seeded _per_buf tree at step=7 must draw THESE indices forever
# (and batch 0's IS weights round to THESE values). If either literal
# moves, seeded device-PER runs silently change their sampling stream.
FROZEN_DEVICE_PER_IDX = [[3, 12, 25, 37], [7, 18, 30, 40], [9, 21, 33, 46]]
FROZEN_DEVICE_PER_W0 = [0.49359, 0.51721, 0.50744, 0.50252]


class TestHostTreeStreamParity:
    @pytest.mark.parametrize("backend", ["numpy", "auto"])
    def test_frozen_stream_and_multi_dispatch_parity(self, backend):
        """Device tree vs host sum-tree over 3 draw→writeback rounds:
        identical index draws (exact), IS weights and post-writeback
        priorities at f32 resolution, max-priority tracking — the
        device-side draw pinned by frozen literals, on both host tree
        backends."""
        host = _per_buf(backend)
        tree = _tree_from_buf(host)
        key = jax.random.PRNGKey(7)

        draw = jax.jit(
            lambda lane, k: dper.lane_draw(lane, k, K, B, jnp.int32(SIZE))
        )
        wb = jax.jit(
            lambda lane, i, p: dper.write_back_lane(
                lane, i, p, host.alpha, host.eps, CAP
            )
        )
        half = dper.tree_width(CAP) // 2
        for step in (7, 8, 9):
            key, k_draw = jax.random.split(key)
            k_lane = jax.random.fold_in(k_draw, jnp.int32(0))
            lane = tree.sums[0]
            idx, p_leaf, total = draw(lane, k_lane)
            # -- the host oracle: same prefixes (threefry is backend-
            # deterministic), descended on the HOST f64 tree
            pre = dper.host_prefixes(k_lane, K, B, float(lane[1]))
            idx_h = host._sum.find_prefixsum_idx(
                np.asarray(pre, np.float64).reshape(-1)
            ).reshape(K, B)
            idx_h = np.minimum(idx_h, SIZE - 1)
            np.testing.assert_array_equal(np.asarray(idx), idx_h)
            if step == 7:
                assert np.asarray(idx).tolist() == FROZEN_DEVICE_PER_IDX
            # -- IS weights: host formula (f64 trees) vs device f32
            beta = host.beta(step)
            p_h = host._sum.get(idx_h.reshape(-1)) / host._sum.sum()
            w_h = (p_h * SIZE) ** (-beta)
            w_h /= (host._min.min() / host._sum.sum() * SIZE) ** (-beta)
            w_d = dper.importance_weights(
                p_leaf, total,
                dper.lane_min_leaf(lane) / total,
                jnp.int32(SIZE), 1,
                dper.beta_at(jnp.int32(step), host.beta0, host.beta_steps),
            )
            np.testing.assert_allclose(
                np.asarray(w_d).reshape(-1), w_h, rtol=2e-5
            )
            if step == 7:
                assert [
                    round(float(x), 5) for x in np.asarray(w_d)[0]
                ] == FROZEN_DEVICE_PER_W0
            # -- write-back: same synthetic TD block through both sides
            td = np.random.default_rng(100 + step).uniform(
                0.05, 2.0, (K, B)
            ).astype(np.float32)
            lane2, mp_local = wb(lane, idx, jnp.asarray(td))
            host.update_priorities(
                idx_h.reshape(-1), td.reshape(-1).astype(np.float64)
            )
            np.testing.assert_allclose(
                np.asarray(lane2[half: half + SIZE]),
                np.asarray(host._sum.get(np.arange(SIZE)), np.float64),
                rtol=2e-6,
            )
            tree = dper.DevicePerTree(
                lane2[None], jnp.maximum(tree.max_priority, mp_local)
            )
            assert (
                abs(float(tree.max_priority) - host._max_priority) < 1e-5
            )

    def test_beta_matches_host_schedule(self):
        host = _per_buf("numpy")
        for step in (0, 1, 50_000, 100_000, 200_000):
            assert abs(
                float(dper.beta_at(jnp.int32(step), host.beta0,
                                   host.beta_steps))
                - host.beta(step)
            ) < 1e-6


# ---------------------------------------------------------- pallas backend
class TestPallasDescent:
    def test_matches_xla_descent(self):
        """The kernel's counting formulation equals the tree descent on
        seeded mass (incl. a non-pow2 capacity → padded leaves, and draw
        counts off the 128 tile)."""
        from d4pg_tpu.ops.pallas_tree import find_prefix_pallas

        r = np.random.default_rng(2)
        cap = 48  # L = 64, padded to 128 lanes in-kernel
        pri = r.uniform(0.1, 3.0, cap)
        lane = dper.set_leaves(
            jnp.zeros(dper.tree_width(cap), jnp.float32),
            jnp.arange(cap, dtype=jnp.int32),
            jnp.asarray(pri, jnp.float32),
            cap,
        )
        half = dper.tree_width(cap) // 2
        pre = jnp.asarray(
            r.uniform(0.0, float(lane[1]) * (1 - 1e-6), (3, 7)), jnp.float32
        )
        idx_x = dper.descend_prefix(lane, pre)
        idx_p = find_prefix_pallas(lane[half:], pre, interpret=True)
        np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))

    def test_lane_draw_backend_equivalence(self):
        """The full draw path (prefixes + descent + clamp) is backend-
        invariant on the frozen stream."""
        host = _per_buf("numpy")
        tree = _tree_from_buf(host)
        key = jax.random.fold_in(
            jax.random.split(jax.random.PRNGKey(7))[1], jnp.int32(0)
        )
        idx_x, _, _ = dper.lane_draw(
            tree.sums[0], key, K, B, jnp.int32(SIZE), tree_backend="xla"
        )
        idx_p, _, _ = dper.lane_draw(
            tree.sums[0], key, K, B, jnp.int32(SIZE),
            tree_backend="pallas", interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_x))
        assert np.asarray(idx_x).tolist() == FROZEN_DEVICE_PER_IDX


# ------------------------------------------------------- sharded bit-parity
def _small_cfg(**kw) -> D4PGConfig:
    base = dict(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(16, 16),
        dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0),
    )
    base.update(kw)
    return D4PGConfig(**base)


def _fill_uniform(buf, n, seed=0):
    r = np.random.default_rng(seed)
    buf.add_batch(
        Transition(
            r.normal(size=(n, 3)).astype(np.float32),
            r.uniform(-1, 1, (n, 1)).astype(np.float32),
            r.uniform(-1, 0, n).astype(np.float32),
            r.normal(size=(n, 3)).astype(np.float32),
            np.full(n, 0.99, np.float32),
        )
    )


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb)
    )


class TestShardedDevicePerParity:
    def test_byte_identical_vs_single_device_oracle(self):
        """The PR-9 acceptance contract, extended to PER: the 8-way mesh
        device-PER megastep (shard-local subtrees + fixed-order root
        combine) is BIT-EXACT — TrainState, subtree lanes, AND the
        max-priority scalar — vs the same body under vmap over striped
        lanes, across multiple draw→train→write-back dispatches."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from d4pg_tpu.parallel import make_mesh, shard_train_state
        from d4pg_tpu.replay.device_ring import (
            ShardedDeviceRingSync,
            device_ring_init,
            striped_lanes,
        )
        from d4pg_tpu.runtime.megastep import (
            make_megastep_device_per_oracle,
            make_megastep_device_per_sharded,
        )

        cfg = _small_cfg()
        D, C, k, b = 8, 64, 2, 16
        buf = ReplayBuffer(C, 3, 1)
        _fill_uniform(buf, C)
        mesh = make_mesh(dp=D, tp=1)
        ring = device_ring_init(C, 3, 1, mesh=mesh)
        sync = ShardedDeviceRingSync(buf, mesh)
        dps = dper.DevicePerSync(C, cfg.per_alpha, mesh=mesh)
        sync.tree_hook = dps.on_chunk
        ring = sync.flush(ring)  # mirrors rows AND seeds every leaf
        # oracle side: striped lane view + an identically seeded lane tree
        lanes = striped_lanes(buf, D)
        tree_o = dper.tree_from_priorities(
            np.ones(C, np.float32), C, n_shards=D
        )
        mega = make_megastep_device_per_sharded(cfg, k, b, mesh)
        oracle = make_megastep_device_per_oracle(cfg, k, b, D)
        s_mesh = shard_train_state(
            create_train_state(cfg, jax.random.PRNGKey(1)), mesh
        )
        s_or = create_train_state(cfg, jax.random.PRNGKey(1))
        key_m = jax.device_put(
            jax.random.PRNGKey(7), NamedSharding(mesh, P())
        )
        key_o = jax.random.PRNGKey(7)
        tree_m = dps.tree
        for _ in range(3):
            s_mesh, tree_m, key_m, _m = mega(s_mesh, ring, tree_m, key_m)
            s_or, tree_o, key_o, _o = oracle(s_or, lanes, tree_o, key_o)
        assert _leaves_equal(s_mesh, s_or)
        assert np.array_equal(
            np.asarray(jax.device_get(tree_m.sums)),
            np.asarray(jax.device_get(tree_o.sums)),
        )
        assert np.array_equal(
            np.asarray(jax.device_get(tree_m.max_priority)),
            np.asarray(jax.device_get(tree_o.max_priority)),
        )

    def test_sharded_tree_lanes_land_on_dp(self):
        """The PER_TREE_RULES placement: subtree lanes split over "dp"
        (one per device), the max-priority scalar replicated."""
        from d4pg_tpu.parallel import make_mesh

        mesh = make_mesh(dp=4, tp=1)
        dps = dper.DevicePerSync(64, 0.6, mesh=mesh)
        assert not dps.tree.sums.sharding.is_fully_replicated
        assert len(dps.tree.sums.sharding.device_set) == 4
        assert dps.tree.max_priority.sharding.is_fully_replicated
        # each device holds exactly one [1, 2L] lane
        shard_shapes = {
            s.data.shape for s in dps.tree.sums.addressable_shards
        }
        assert shard_shapes == {(1, dper.tree_width(16))}

    def test_capacity_not_divisible_raises(self):
        from d4pg_tpu.parallel import make_mesh

        with pytest.raises(ValueError, match="divisible"):
            dper.device_per_init(62, n_shards=4, mesh=make_mesh(dp=4, tp=1))


# ------------------------------------------------------- trainer contracts
def _trainer_cfg(log_dir: str, **kw) -> TrainConfig:
    agent = D4PGConfig(hidden_sizes=(16, 16), dist=DistConfig(num_atoms=11))
    base = dict(
        env="pendulum",
        num_envs=2,
        total_steps=8,
        warmup_steps=48,
        batch_size=8,
        steps_per_dispatch=2,
        eval_interval=1000,
        eval_episodes=1,
        checkpoint_interval=100_000,
        replay_capacity=512,
        prioritized=True,
        tree_backend="numpy",
        agent=agent,
        log_dir=log_dir,
        concurrent_eval=False,
        seed=3,
        replay_placement="device",
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


class TestTrainerDevicePer:
    def test_device_keeps_per_with_device_tree(self, tmp_path, capsys):
        """The ISSUE-14 flip: `--replay-placement device` with PER no
        longer downgrades — the host buffer is a plain ring and the
        priority structure is the device tree."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_trainer_cfg(str(tmp_path / "d")))
        try:
            assert t.config.prioritized is True
            assert isinstance(t.buffer, ReplayBuffer)
            assert not isinstance(t.buffer, PrioritizedReplayBuffer)
            assert t._dev_per is not None
            # bound methods compare equal (identity is per-access)
            assert t._ring_sync.tree_hook == t._dev_per.on_chunk
        finally:
            t.close()
        assert "disabling PER" not in capsys.readouterr().out

    def test_guards_clean_with_per(self, tmp_path):
        """Device-PER under --debug-guards: the steady-state dispatch
        runs under the ZERO-transfer budget with prioritized replay ON,
        compile budgets flat (megastep=1, ring_ingest=1, tree_ingest=1 —
        one fixed program each), zero ledger holds, and the device tree
        actually carries the write-backs (max_priority moved off its
        1.0 seed)."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_trainer_cfg(str(tmp_path / "g"), debug_guards=True))
        try:
            t.train()
            assert t._megastep_warm
            counts = t.sentinel.counts()
            assert counts["megastep"] == 1
            assert counts["ring_ingest"] == 1
            assert counts["tree_ingest"] == 1
            assert t._ledger.stats()["active_holds"] == 0
            assert t._ledger.stats()["trips"] == 0
            assert float(t._dev_per.tree.max_priority) != 1.0
            # the tree's mass covers exactly the mirrored rows
            pa, _ = t._dev_per.snapshot_host()
            assert (pa > 0).sum() == len(t.buffer)
        finally:
            t.close()

    def test_hybrid_still_works_as_legacy(self, tmp_path, capsys):
        """Hybrid negotiates (legacy host-tree oracle), says so, and
        keeps its PrioritizedReplayBuffer."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _trainer_cfg(str(tmp_path / "h"), replay_placement="hybrid")
        )
        try:
            assert isinstance(t.buffer, PrioritizedReplayBuffer)
        finally:
            t.close()
        assert "legacy host sum-tree" in capsys.readouterr().out

    @pytest.mark.slow
    def test_snapshot_restores_tree_priorities(self, tmp_path):
        """--snapshot-replay + --resume round-trips the device tree: the
        sidecar (device_per.npz) restores the exact α'd leaf priorities
        and max-priority instead of re-seeding at max."""
        from d4pg_tpu.runtime.trainer import Trainer

        d = str(tmp_path / "snap")
        t = Trainer(
            _trainer_cfg(
                d, snapshot_replay=True, total_steps=4,
                checkpoint_interval=4,
            )
        )
        try:
            t.train()
            t._save_checkpoint()
            pa0, mp0 = t._dev_per.snapshot_host()
        finally:
            t.close()
        assert (pa0 > 0).any()
        t2 = Trainer(
            _trainer_cfg(
                d, snapshot_replay=True, total_steps=8, resume=True,
            )
        )
        try:
            pa1, mp1 = t2._dev_per.snapshot_host()
            np.testing.assert_array_equal(pa0, pa1)
            assert mp0 == mp1
        finally:
            t2.close()

    @pytest.mark.slow
    def test_sharded_trainer_guards_clean_with_per(self, tmp_path):
        """device+PER composes with --dp over the 8-way virtual mesh
        under --debug-guards (the acceptance-run shape, miniaturized)."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _trainer_cfg(
                str(tmp_path / "dp"), dp=8, batch_size=16,
                debug_guards=True,
            )
        )
        try:
            t.train()
            counts = t.sentinel.counts()
            assert counts["megastep"] == 1
            assert counts["ring_ingest"] == 1
            assert counts["tree_ingest"] == 1
            assert t._dev_per.tree.sums.shape[0] == 8
        finally:
            t.close()

    @pytest.mark.slow
    def test_pallas_backend_trains(self, tmp_path):
        """The Pallas descent is reachable end-to-end from the config
        (interpret mode on CPU) and passes the same guard contract."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _trainer_cfg(
                str(tmp_path / "p"), device_tree_backend="pallas",
                total_steps=4, debug_guards=True,
            )
        )
        try:
            t.train()
            assert t.sentinel.counts()["megastep"] == 1
        finally:
            t.close()
