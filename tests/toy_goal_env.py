"""A tiny goal-dict gymnasium env for exercising the HER pool path.

Point on a 2-D plane; action moves it; success when within 0.1 of the goal.
Sparse reward 0/-1 and an ``is_success`` info flag — the same contract as the
robotics envs the reference's HER loop targets (``main.py:144-184``).

Made with the module-prefixed id ``"toy_goal_env:ToyGoal-v0"`` so that
spawned actor-pool workers can resolve it: gymnasium imports this module
(which registers the env) in the child process before ``gym.make``.
"""

import gymnasium as gym
import numpy as np
from gymnasium import spaces


class ToyGoalEnv(gym.Env):
    def __init__(self):
        box = spaces.Box(-1.0, 1.0, (2,), np.float32)
        self.observation_space = spaces.Dict(
            {"observation": box, "achieved_goal": box, "desired_goal": box}
        )
        self.action_space = spaces.Box(-1.0, 1.0, (2,), np.float32)
        self._pos = np.zeros(2, np.float32)
        self._goal = np.zeros(2, np.float32)

    def _obs(self):
        return {
            "observation": self._pos.copy(),
            "achieved_goal": self._pos.copy(),
            "desired_goal": self._goal.copy(),
        }

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._pos = self.np_random.uniform(-1, 1, 2).astype(np.float32)
        self._goal = self.np_random.uniform(-1, 1, 2).astype(np.float32)
        return self._obs(), {}

    def compute_reward(self, achieved_goal, desired_goal, info):
        d = np.linalg.norm(np.asarray(achieved_goal) - np.asarray(desired_goal), axis=-1)
        return -(d >= 0.1).astype(np.float32)

    def step(self, action):
        self._pos = np.clip(self._pos + 0.2 * np.asarray(action, np.float32), -1, 1)
        r = float(self.compute_reward(self._pos, self._goal, {}))
        success = r == 0.0
        return self._obs(), r, bool(success), False, {"is_success": success}


gym.register(id="ToyGoal-v0", entry_point=ToyGoalEnv, max_episode_steps=25)
