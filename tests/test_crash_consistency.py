"""Crash-consistent checkpointing: the manifest commit record, verify-on-
restore fallback, torn side files — and the full-contract subprocess
regression (kill -9 at a randomized instant mid-run, then --resume).

Fast tests drive :class:`CheckpointManager` directly with a tiny
TrainState; the slow test SIGKILLs a live ``train.py`` and proves the
resume handshake end to end (rc 0, monotone step counter, fallback to the
newest intact step).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.agent import create_train_state
from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.chaos import truncate_checkpoint_step
from d4pg_tpu.runtime.checkpoint import (
    CheckpointManager,
    load_trainer_meta,
    save_trainer_meta,
    trainer_meta_path,
)

CFG = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(8, 8))


def _state(step=0):
    st = create_train_state(CFG, jax.random.PRNGKey(0))
    return st.replace(step=st.step + step) if step else st


def _mgr(tmp_path, **kw):
    return CheckpointManager(str(tmp_path / "checkpoints"), **kw)


def _save_attested(mgr, step, state):
    mgr.save(step, state)
    mgr.wait()
    mgr.write_manifest(step)


class TestManifest:
    def test_write_and_verify_roundtrip(self, tmp_path):
        mgr = _mgr(tmp_path)
        _save_attested(mgr, 1, _state())
        ok, why, warnings = mgr.verify_step(1)
        assert ok, why
        assert warnings == []
        m = mgr.load_manifest(1)
        assert m["step"] == 1 and m["files"]  # digests every orbax file
        mgr.close()

    def test_truncation_detected_and_fallback(self, tmp_path):
        mgr = _mgr(tmp_path)
        _save_attested(mgr, 1, _state(1))
        _save_attested(mgr, 2, _state(2))
        truncate_checkpoint_step(mgr.step_dir(2))
        ok, why, _ = mgr.verify_step(2)
        assert not ok and ("truncated" in why or "digest" in why)
        restored, step, fallbacks = mgr.restore_verified(_state())
        assert step == 1 and len(fallbacks) == 1
        assert int(jax.device_get(restored.step)) == int(
            jax.device_get(_state(1).step)
        )
        # the corrupt newer step was PRUNED: a resumed run re-saving at
        # step 2 must not collide with the dead branch
        assert mgr.all_steps() == [1]
        assert not os.path.exists(mgr.manifest_path(2))
        _save_attested(mgr, 2, _state(2))
        _, step2, fb2 = mgr.restore_verified(_state())
        assert step2 == 2 and fb2 == []
        mgr.close()

    def test_uncommitted_step_skipped(self, tmp_path):
        """kill -9 between the Orbax save and the manifest write leaves the
        newest step unattested: restore must use the previous intact one."""
        mgr = _mgr(tmp_path)
        _save_attested(mgr, 1, _state(1))
        mgr.save(2, _state(2))
        mgr.wait()  # step 2 fully on disk, but NO manifest = never committed
        _, step, fallbacks = mgr.restore_verified(_state())
        assert step == 1
        assert fallbacks and "no manifest" in fallbacks[0]
        assert mgr.all_steps() == [1]  # the uncommitted branch was pruned
        mgr.close()

    def test_legacy_run_without_manifests_still_restores(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(1, _state(1))
        mgr.save(2, _state(2))
        mgr.wait()
        _, step, fallbacks = mgr.restore_verified(_state())
        assert step == 2 and fallbacks == []  # pre-manifest runs: best effort
        mgr.close()

    def test_delete_removes_manifest_with_bytes(self, tmp_path):
        mgr = _mgr(tmp_path)
        _save_attested(mgr, 1, _state(1))
        assert os.path.exists(mgr.manifest_path(1))
        mgr.delete(1)
        assert not os.path.exists(mgr.manifest_path(1))
        mgr.close()

    def test_manifest_pruned_with_max_to_keep(self, tmp_path):
        mgr = _mgr(tmp_path, max_to_keep=2)
        for s in (1, 2, 3):
            _save_attested(mgr, s, _state(s))
        live = set(mgr.all_steps())
        assert 1 not in live
        assert not os.path.exists(mgr.manifest_path(1))
        assert os.path.exists(mgr.manifest_path(3))
        mgr.close()

    def test_stale_log_dir_save_is_loud_not_silent(self, tmp_path):
        """Orbax silently skips saves at steps older than the newest on
        disk — the exact signature of reusing another run's log dir
        without --resume. That used to train forever while never
        checkpointing; it must raise with the remedy instead. A re-save
        at the CURRENT latest step (preemption right after a periodic
        save) stays legitimately quiet."""
        mgr = _mgr(tmp_path)
        _save_attested(mgr, 2000, _state(2000))
        with pytest.raises(RuntimeError, match="--resume, or use a fresh"):
            mgr.save(4, _state(4))
        mgr.save(2000, _state(2000))  # same-step re-save: no error
        mgr.wait()
        mgr.close()

    def test_side_file_drift_warns_but_restores(self, tmp_path):
        """Crash between a NEWER save's meta write and its manifest: the
        chosen older step sees a drifted side file — warn, don't fail."""
        mgr = _mgr(tmp_path)
        log_dir = str(tmp_path)
        save_trainer_meta(log_dir, 100, 1.0)
        mgr.save(1, _state(1))
        mgr.wait()
        mgr.write_manifest(1, side_files=[trainer_meta_path(log_dir)])
        save_trainer_meta(log_dir, 999, 2.0)  # the "newer crashed save"
        ok, _, warnings = mgr.verify_step(1)
        assert ok and warnings and "differs" in warnings[0]
        _, step, fallbacks = mgr.restore_verified(_state())
        assert step == 1 and fallbacks == []
        mgr.close()


class TestTornMeta:
    def test_missing_meta_is_empty(self, tmp_path):
        assert load_trainer_meta(str(tmp_path)) == {}

    def test_torn_meta_degrades_to_empty_with_warning(self, tmp_path, capsys):
        """Satellite bugfix: a torn/corrupt trainer_meta.json used to raise
        JSONDecodeError and kill the resume — it must degrade to {}."""
        path = trainer_meta_path(str(tmp_path))
        os.makedirs(os.path.dirname(path))
        path_obj = open(path, "w")
        path_obj.write('{"env_steps": 123, "ewma_re')  # torn mid-write
        path_obj.close()
        assert load_trainer_meta(str(tmp_path)) == {}
        assert "unreadable/corrupt" in capsys.readouterr().out

    def test_intact_meta_roundtrips(self, tmp_path):
        os.makedirs(tmp_path / "checkpoints")
        save_trainer_meta(str(tmp_path), 7, 1.5, extra={"x": 1})
        assert load_trainer_meta(str(tmp_path)) == {
            "env_steps": 7, "ewma_return": 1.5, "x": 1,
        }


def test_corrupt_replay_snapshot_raises_caught_types(tmp_path):
    """The trainer's resume wraps buffer.restore in (OSError, ValueError,
    KeyError, BadZipFile) — a truncated npz must raise within that set so
    resume degrades instead of dying."""
    import zipfile

    from d4pg_tpu.replay import ReplayBuffer

    snap = tmp_path / "replay.npz"
    buf = ReplayBuffer(64, 3, 1)
    buf.add(np.zeros(3), np.zeros(1), 0.0, np.zeros(3), 1.0)
    buf.snapshot(str(snap))
    raw = snap.read_bytes()
    snap.write_bytes(raw[: len(raw) // 2])  # torn mid-write
    with pytest.raises(
        (OSError, ValueError, KeyError, zipfile.BadZipFile)
    ):
        ReplayBuffer(64, 3, 1).restore(str(snap))


# ---------------------------------------------------------- the full contract
@pytest.mark.slow
def test_kill9_mid_checkpointing_run_then_resume_restores_intact_step(tmp_path):
    """ISSUE-5 acceptance: kill -9 a checkpointing train.py at a randomized
    instant, then --resume — it must come back with rc 0, restore the
    newest INTACT step (falling back past any partial save), and keep the
    step counter monotone."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
        and "AXON" not in k
        and "TPU" not in k
    }
    env["JAX_PLATFORMS"] = "cpu"
    run = str(tmp_path / "run")
    ckpt_dir = os.path.join(run, "checkpoints")
    args = [
        sys.executable, "train.py",
        "--env", "Pendulum-v1", "--hidden-sizes", "16,16",
        "--total-steps", "100000", "--warmup", "16",
        "--bsize", "8", "--rmsize", "512",
        "--eval-interval", "100000", "--checkpoint-interval", "8",
        "--num-envs", "1", "--snapshot-replay", "--log-dir", run,
    ]
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=cwd,
    )
    lines = []
    th = threading.Thread(
        target=lambda: lines.extend(proc.stdout), daemon=True
    )
    th.start()

    def manifests():
        try:
            return sorted(
                int(f[len("manifest_"):-len(".json")])
                for f in os.listdir(ckpt_dir)
                if f.startswith("manifest_") and f.endswith(".json")
            )
        except (OSError, ValueError):
            return []

    # Wait until at least one checkpoint COMMITTED, then kill at a seeded-
    # random instant within the next checkpoint interval — the kill lands
    # mid-save, mid-snapshot, or between, and resume must survive all of
    # them.
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline and not manifests():
        if proc.poll() is not None:
            pytest.fail("train.py died early:\n" + "".join(lines)[-3000:])
        time.sleep(0.2)
    committed = manifests()
    assert committed, "no checkpoint committed within 300 s"
    rng = np.random.default_rng(0xD4)
    time.sleep(float(rng.uniform(0.0, 2.0)))
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    th.join(timeout=10)
    attested_after_kill = manifests()
    assert attested_after_kill, "kill erased every manifest?"

    resume_to = max(attested_after_kill) + 8
    out = subprocess.run(
        args[:6] + [
            "--total-steps", str(resume_to), "--warmup", "16",
            "--bsize", "8", "--rmsize", "512",
            "--eval-interval", "100000", "--checkpoint-interval", "8",
            "--num-envs", "1", "--snapshot-replay", "--log-dir", run,
            "--resume",
        ],
        capture_output=True, text=True, env=env, cwd=cwd, timeout=600,
    )
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert "[checkpoint] resumed from step" in out.stdout
    restored = int(
        out.stdout.split("[checkpoint] resumed from step", 1)[1].split()[0]
    )
    # the restored step is one the manifest set attests (newest intact —
    # a crash-torn newer step is skipped, logged as a fallback)
    assert restored in attested_after_kill
    assert restored == max(
        s for s in attested_after_kill if s <= restored
    )
    # monotone: the resumed leg ran past the restored step and
    # re-checkpointed at a strictly later one
    final = manifests()
    assert final and max(final) >= restored
