"""Multi-host learner (ISSUE 17): one mesh across processes, per-host ingest.

Contracts under test, in dependency order:

1. DEGENERATE EXACTNESS (fast, tier-1): ``MultihostRingSync`` on a
   single-process 8-device mesh (P=1, L=D) is byte-identical to
   ``ShardedDeviceRingSync`` fed the same host stream — same striped
   layout, same compiled ingest program, the cursor all-gather collapses
   to a local read. Its snapshot pair round-trips: ``gather_snapshot``
   reproduces the exact ``ReplayBuffer.snapshot`` npz layout and
   ``deal_snapshot`` is its inverse.
2. LAYOUT ALGEBRA (fast, tier-1, pure host): the gapless-total formula
   equals a brute-force simulation of the interleaved global write
   stream, and the per-process snapshot deal partitions the global rows
   exactly (disjoint cover, correct local slots) for P>1 — the math that
   makes per-host ingest exact, tested without spawning processes.
3. TOPOLOGY BIT-EXACTNESS (slow, THE tentpole contract): a 2-process ×
   4-device mesh — real ``jax.distributed`` over gloo — produces
   bit-identical TrainState (params, targets, BOTH Adam moment sets),
   device ring, device-PER tree, ``det_pmean`` reductions and
   ``fold_in(global shard index)`` in-kernel draws vs the 8-device
   single-process run of the SAME code, after multiple megastep
   dispatches interleaved with per-host ingest, with a zero-transfer
   steady-state dispatch on both topologies.
4. ELASTIC RESUME (slow): a run checkpointed on 2×4 resumes on 1×8 and
   back on 2×4 through the real CLI — replay snapshot and device-PER
   priority sidecar byte-compare across the topology change.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "benchmarks"))

from multihost_microbench import (  # noqa: E402
    compare_npz,
    child_env,
    free_port,
    run_exact_topology,
)

from d4pg_tpu.parallel import make_mesh  # noqa: E402
from d4pg_tpu.replay.device_ring import (  # noqa: E402
    MultihostRingSync,
    ShardedDeviceRingSync,
    device_ring_init,
)
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fill(buf, n, seed=0):
    r = np.random.default_rng(seed)
    obs_dim = buf.obs.shape[1]
    act_dim = buf.action.shape[1]
    buf.add_batch(
        Transition(
            r.normal(size=(n, obs_dim)).astype(np.float32),
            r.uniform(-1, 1, (n, act_dim)).astype(np.float32),
            r.uniform(-1, 0, n).astype(np.float32),
            r.normal(size=(n, obs_dim)).astype(np.float32),
            np.full(n, 0.99, np.float32),
        )
    )


# --------------------------------------------- 1. degenerate exactness (P=1)
class TestMultihostSyncDegenerate:
    """P=1 is a real point of the multihost algebra (L=D, base=0), so the
    whole class runs in-process on the 8-device virtual mesh and tier-1
    pins it without spawning processes."""

    FIELDS = ("obs", "action", "reward", "next_obs", "discount")

    def test_flush_matches_sharded_sync_bitwise(self):
        D, C = 8, 64
        mesh = make_mesh(dp=D, tp=1)
        buf_m, buf_s = ReplayBuffer(C, 3, 1), ReplayBuffer(C, 3, 1)
        ring_m = device_ring_init(C, 3, 1, mesh=mesh)
        ring_s = device_ring_init(C, 3, 1, mesh=mesh)
        sync_m = MultihostRingSync(buf_m, mesh, chunk_cap=32)
        sync_s = ShardedDeviceRingSync(buf_s, mesh, chunk_cap=32)
        # uneven fills + a wrap: the layouts must stay identical throughout
        for n, seed in ((41, 1), (17, 2), (30, 3)):
            _fill(buf_m, n, seed=seed)
            _fill(buf_s, n, seed=seed)
            ring_m = sync_m.flush(ring_m)
            ring_s = sync_s.flush(ring_s)
            for f in self.FIELDS + ("size",):
                np.testing.assert_array_equal(
                    np.asarray(getattr(ring_m, f)),
                    np.asarray(getattr(ring_s, f)),
                )

    def test_single_ingest_compile_across_flushes(self):
        """Same recompile budget as the single-process sync: the sentinel's
        ring_ingest == 1 contract holds per process."""
        mesh = make_mesh(dp=8, tp=1)
        buf = ReplayBuffer(64, 3, 1)
        ring = device_ring_init(64, 3, 1, mesh=mesh)
        sync = MultihostRingSync(buf, mesh, chunk_cap=16)
        for seed in range(4):
            _fill(buf, 11, seed=seed)
            ring = sync.flush(ring)
        assert sync.ingest_fn._cache_size() == 1

    def test_gather_snapshot_matches_buffer_snapshot(self, tmp_path):
        """gather_snapshot reproduces the exact ReplayBuffer.snapshot npz
        layout — rows in global slot order plus pos/size — so multi-host
        checkpoints restore onto ANY topology."""
        D, C = 8, 64
        mesh = make_mesh(dp=D, tp=1)
        buf = ReplayBuffer(C, 3, 1)
        ring = device_ring_init(C, 3, 1, mesh=mesh)
        sync = MultihostRingSync(buf, mesh, chunk_cap=32)
        _fill(buf, 50, seed=4)
        _fill(buf, 30, seed=5)  # wraps: pos=16, size=C
        ring = sync.flush(ring)
        snap = sync.gather_snapshot(ring)
        path = str(tmp_path / "replay.npz")
        buf.snapshot(path)
        with np.load(path) as z:
            for k in self.FIELDS + ("pos", "size"):
                np.testing.assert_array_equal(snap[k], z[k])
                assert snap[k].dtype == z[k].dtype, k

    def test_deal_snapshot_roundtrip(self, tmp_path):
        """deal → flush → gather is the identity on snapshot bytes: the
        elastic-resume guarantee, in-process."""
        D, C = 8, 64
        mesh = make_mesh(dp=D, tp=1)
        src = ReplayBuffer(C, 3, 1)
        _fill(src, 80, seed=6)  # wrapped source
        path = str(tmp_path / "replay.npz")
        src.snapshot(path)

        buf = ReplayBuffer(C, 3, 1)
        sync = MultihostRingSync(buf, mesh, chunk_cap=32)
        with np.load(path) as z:
            n = sync.deal_snapshot(z)
        assert n == C
        assert buf.total_added == src.total_added
        ring = sync.flush(device_ring_init(C, 3, 1, mesh=mesh))
        snap = sync.gather_snapshot(ring)
        with np.load(path) as z:
            for k in self.FIELDS + ("pos", "size"):
                np.testing.assert_array_equal(snap[k], z[k])


# ------------------------------------------------- 2. layout algebra (P>1)
def _bare_sync(P_, L_, p, buf=None):
    """A MultihostRingSync shell for process ``p`` of a P_×L_ topology —
    the host-side layout algebra (_gapless_total, deal_snapshot) needs no
    mesh, so P>1 is testable in one process."""
    s = MultihostRingSync.__new__(MultihostRingSync)
    s.n_processes = P_
    s.local_shards = L_
    s.n_shards = P_ * L_
    s.shard_lo = p * L_
    s._buffer = buf
    s.host_capacity = buf.capacity if buf is not None else 0
    s.capacity = s.host_capacity * P_
    s.local_capacity = s.capacity // s.n_shards if buf is not None else 0
    s._synced = 0
    return s


class TestMultihostLayoutAlgebra:
    @pytest.mark.parametrize("P_,L_", [(2, 4), (4, 2), (2, 2), (3, 2)])
    def test_gapless_total_matches_brute_force(self, P_, L_):
        """Host p's k-th local write is global write (k//L)*D + p*L + (k%L);
        the agreed fill count must be the longest fully-landed prefix of
        that interleaved stream — no more (a gap would publish a row some
        host never wrote), no less."""
        D = P_ * L_
        sync = _bare_sync(P_, L_, 0)
        rng = np.random.default_rng(0)
        for _ in range(50):
            totals = rng.integers(0, 40, size=P_)
            landed = set()
            for p in range(P_):
                for k in range(int(totals[p])):
                    landed.add((k // L_) * D + p * L_ + (k % L_))
            T = 0
            while T in landed:
                T += 1
            assert sync._gapless_total(totals) == T, totals

    @pytest.mark.parametrize("P_,L_,T", [(2, 4, 0), (2, 4, 5), (2, 4, 13),
                                         (2, 4, 32), (4, 2, 29), (2, 2, 39)])
    def test_deal_partitions_global_rows_exactly(self, P_, L_, T):
        """deal_snapshot on each process of a P_×L_ topology: local slots
        hold exactly the global slots the striping assigns, the per-host
        shares are a disjoint cover of the snapshot rows, and the
        reconstructed lifetime cursors re-derive the same global T."""
        D = P_ * L_
        C = 32
        host_cap = C // P_
        size = min(T, C)
        pos = T % C
        data = {
            "size": np.asarray(size), "pos": np.asarray(pos),
            "obs": np.arange(size, dtype=np.float32).reshape(size, 1),
            "action": np.zeros((size, 1), np.float32),
            "reward": np.zeros(size, np.float32),
            "next_obs": np.zeros((size, 1), np.float32),
            "discount": np.zeros(size, np.float32),
        }
        # Wrapped snapshots reconstruct T as pos+capacity (same rule as
        # ReplayBuffer.restore) — recompute the T the deal actually sees.
        T_seen = pos + C if size == C else size
        covered = []
        totals = []
        for p in range(P_):
            buf = ReplayBuffer(host_cap, 1, 1)
            sync = _bare_sync(P_, L_, p, buf)
            n = sync.deal_snapshot(data)
            totals.append(buf.total_added)
            base = p * L_
            m = np.arange(n)
            j = (m // L_) * D + base + (m % L_)
            # every dealt global slot must be a snapshot row
            assert (j < size).all()
            np.testing.assert_array_equal(buf.obs[:n, 0], j.astype(np.float32))
            covered.append(j)
            t_p = (T_seen // D) * L_ + int(np.clip(T_seen % D - base, 0, L_))
            assert buf.total_added == t_p
        allj = np.concatenate(covered) if covered else np.array([], np.int64)
        assert len(allj) == len(set(allj.tolist()))  # disjoint
        assert len(allj) == size                     # ...and a full cover
        # the reconstructed cursors agree on the same global fill count
        sync0 = _bare_sync(P_, L_, 0, ReplayBuffer(host_cap, 1, 1))
        assert min(sync0._gapless_total(np.asarray(totals)), C) == size


# ------------------------------------- 3. topology bit-exactness (tentpole)
@pytest.mark.slow
def test_two_process_mesh_bit_exact_vs_single_process_oracle(tmp_path):
    """THE tentpole contract: the 2-process × 4-device global mesh — real
    jax.distributed init, per-host ingest into local shards only, multiple
    dispatches — is BIT-exact vs the 8-device single-process run: every
    TrainState leaf (params, targets, both Adam moment sets), the
    assembled ring, the device-PER tree sidecar, det_pmean reductions,
    fold_in(global shard index) draws, and the loss metrics. Each
    topology also proves the zero-transfer steady state (the child
    dispatches once under no_transfers). Drives the same child the
    committed multihost_microbench.json attestation is generated from."""
    single = run_exact_topology(str(tmp_path), 1)
    multi = run_exact_topology(str(tmp_path), 2)
    res = compare_npz(single, multi)
    assert res["mismatches"] == []
    assert res["state_leaves"] > 0
    assert res["keys_compared"] > res["state_leaves"]  # ring/tree/draws too


# --------------------------------------------------- 4. elastic resume (CLI)
def _cli_args(d: str, steps: int, resume: bool) -> list:
    args = [
        sys.executable, "train.py", "--env", "pendulum",
        "--hidden-sizes", "16,16", "--n-atoms", "11",
        "--total-steps", str(steps), "--warmup", "24", "--bsize", "8",
        "--rmsize", "256", "--dp", "8", "--replay-placement", "device",
        "--num-envs", "2", "--eval-interval", "100000",
        "--eval-episodes", "1", "--checkpoint-interval", "12",
        "--snapshot-replay", "--no-concurrent-eval",
        "--log-dir", d, "--seed", "3",
    ]
    if resume:
        args.append("--resume")
    return args


def _run_leg(d: str, steps: int, nprocs: int, resume: bool) -> list:
    env = child_env()
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nprocs}"
    )
    env["PYTHONPATH"] = REPO
    args = _cli_args(d, steps, resume)
    if nprocs > 1:
        coord = f"localhost:{free_port()}"
        procs = [
            subprocess.Popen(
                args + ["--coordinator", coord, "--num-processes",
                        str(nprocs), "--process-id", str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, text=True, cwd=REPO,
            )
            for rank in range(nprocs)
        ]
    else:
        procs = [
            subprocess.Popen(
                args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, text=True, cwd=REPO,
            )
        ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for rank, (p, text) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"leg nprocs={nprocs} rank {rank}:\n{text}"
        assert "done:" in text
    return outs


@pytest.mark.slow
def test_elastic_resume_across_topology_changes(tmp_path):
    """2×4 → 1×8 → 2×4 through the real CLI: each leg resumes the previous
    topology's checkpoint (Orbax state re-sharded onto the new mesh,
    replay snapshot dealt/restored, device-PER sidecar reloaded), and the
    sidecar written by the 2-process collective gather byte-round-trips
    through a 1×8 restore."""
    from d4pg_tpu.replay.device_per import DevicePerSync

    d = str(tmp_path / "run")
    _run_leg(d, 24, nprocs=2, resume=False)
    per_path = os.path.join(d, "checkpoints", "device_per.npz")
    replay_path = os.path.join(d, "checkpoints", "replay.npz")
    assert os.path.exists(per_path) and os.path.exists(replay_path)

    # Cross-topology sidecar byte-compare: bytes written by the 2×4
    # collective snapshot, restored onto THIS process's 1×8 mesh, must
    # snapshot back identically (restore_host/snapshot_host inverse pair).
    with np.load(per_path) as z:
        pa24, mp24 = z["priorities_alpha"], float(z["max_priority"])
    per = DevicePerSync(256, alpha=0.6, mesh=make_mesh(dp=8, tp=1))
    per.restore_host(pa24, mp24)
    pa18, mp18 = per.snapshot_host()
    assert pa18.tobytes() == pa24.tobytes()
    assert mp18 == mp24
    # ...and the replay snapshot restores/re-snapshots byte-identically
    # through the single-process buffer (the 1×8 leg's restore path).
    buf = ReplayBuffer(256, 3, 1)
    n = buf.restore(replay_path)
    assert n > 0
    resnap = str(tmp_path / "resnap.npz")
    buf.snapshot(resnap)
    with np.load(replay_path) as a, np.load(resnap) as b:
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    # --total-steps counts THIS invocation's grad steps: leg 2 runs
    # 24 -> 36 on 1x8, leg 3 runs 36 -> 48 back on 2x4.
    out_18 = _run_leg(d, 12, nprocs=1, resume=True)
    assert "resumed from step 24" in out_18[0]
    assert "restored replay snapshot" in out_18[0]
    assert "restored device-PER priorities" in out_18[0]

    out_24 = _run_leg(d, 12, nprocs=2, resume=True)
    for text in out_24:
        assert "resumed from step 36" in text
        assert "restored replay snapshot" in text
        assert "restored device-PER priorities" in text
    # bit-identical completion on both processes of the final leg: the
    # mesh is one SPMD program, so every MODEL metric must agree exactly
    # (the *_per_sec rates are per-process wall-clock and legitimately
    # differ)
    import ast

    done = [
        ast.literal_eval(
            next(
                ln for ln in reversed(t.splitlines())
                if ln.startswith("done:")
            )[len("done:"):].strip()
        )
        for t in out_24
    ]
    model = [
        {k: v for k, v in d.items() if not k.endswith("_per_sec")}
        for d in done
    ]
    assert model[0] == model[1]
