"""Tests for uniform/PER buffers, n-step writer, HER, schedules."""

import numpy as np
import pytest

from d4pg_tpu.replay import (
    HindsightWriter,
    NStepWriter,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    Transition,
    linear_schedule,
)


def _fill(buf, n, obs_dim=3, act_dim=2, rng=None):
    rng = rng or np.random.default_rng(0)
    for i in range(n):
        buf.add(rng.normal(size=obs_dim), rng.normal(size=act_dim), float(i), rng.normal(size=obs_dim), 0.99)


def test_ring_buffer_wraps():
    buf = ReplayBuffer(8, 3, 2)
    _fill(buf, 10)
    assert len(buf) == 8
    # oldest two entries overwritten: rewards now 8,9,2..7 in ring order
    assert set(buf.reward.tolist()) == {8.0, 9.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0}


def test_uniform_sample_shapes():
    buf = ReplayBuffer(100, 3, 2)
    _fill(buf, 50)
    batch = buf.sample(16, np.random.default_rng(0))
    assert batch["obs"].shape == (16, 3)
    assert batch["action"].shape == (16, 2)
    assert batch["reward"].shape == (16,)
    assert batch["discount"].shape == (16,)


def test_per_new_samples_get_max_priority_and_weights_one():
    buf = PrioritizedReplayBuffer(64, 3, 2, alpha=0.6, tree_backend="numpy")
    _fill(buf, 20)
    batch = buf.sample(8, np.random.default_rng(0), step=0)
    # all priorities equal => all IS weights 1
    np.testing.assert_allclose(batch["weights"], 1.0, atol=1e-6)


def test_per_prioritized_sampling_prefers_high_td():
    buf = PrioritizedReplayBuffer(64, 1, 1, alpha=1.0, tree_backend="numpy")
    for i in range(10):
        buf.add(np.array([float(i)]), np.array([0.0]), 0.0, np.array([0.0]), 0.99)
    # slot 3 gets enormous priority
    pri = np.full(10, 1e-3)
    pri[3] = 1e3
    buf.update_priorities(np.arange(10), pri)
    batch = buf.sample(256, np.random.default_rng(1), step=0)
    frac3 = np.mean(batch["obs"][:, 0] == 3.0)
    assert frac3 > 0.95
    # and its IS weight is the smallest
    w = batch["weights"][batch["obs"][:, 0] == 3.0]
    assert np.all(w <= batch["weights"].max())
    assert w.max() < 1e-2


def test_per_stale_writeback_dropped_for_recycled_slots():
    """A write-back whose slot was overwritten since sampling must not stamp
    the NEW transition with the OLD transition's TD priority (the async
    flusher's slot-recycling hazard, advisor round-1 #3)."""
    buf = PrioritizedReplayBuffer(8, 1, 1, alpha=1.0, eps=0.0, tree_backend="numpy")
    _fill(buf, 8, obs_dim=1, act_dim=1)
    batch = buf.sample(4, np.random.default_rng(0), step=0)
    sampled = batch["indices"]
    # recycle every slot (capacity-many fresh writes wrap the whole ring)
    _fill(buf, 8, obs_dim=1, act_dim=1)
    seed = buf._max_priority  # fresh inserts sit at max_priority^alpha
    buf.update_priorities(sampled, np.full(4, 1e-6))
    # all updates dropped: every leaf still carries the fresh-insert seed
    np.testing.assert_allclose(buf._sum.get(np.arange(8)), seed, atol=1e-9)
    # raw arrays (no generation stamp) keep the unconditional behavior
    buf.update_priorities(np.asarray(sampled.idx), np.full(4, 1e-6))
    assert buf._min.min() == pytest.approx(1e-6)


def test_per_live_writeback_applies_with_generation_stamp():
    buf = PrioritizedReplayBuffer(16, 1, 1, alpha=1.0, eps=0.0, tree_backend="numpy")
    _fill(buf, 16, obs_dim=1, act_dim=1)
    batch = buf.sample(6, np.random.default_rng(0), step=0)
    buf.update_priorities(batch["indices"], np.full(6, 0.5))
    assert buf._min.min() == pytest.approx(0.5)


def test_per_beta_anneals():
    buf = PrioritizedReplayBuffer(64, 1, 1, beta0=0.4, beta_steps=100, tree_backend="numpy")
    assert buf.beta(0) == pytest.approx(0.4)
    assert buf.beta(50) == pytest.approx(0.7)
    assert buf.beta(1000) == pytest.approx(1.0)


def test_per_update_priorities_roundtrip():
    buf = PrioritizedReplayBuffer(32, 1, 1, alpha=1.0, eps=0.0, tree_backend="numpy")
    _fill(buf, 4, obs_dim=1, act_dim=1)
    buf.update_priorities(np.array([0, 1, 2, 3]), np.array([1.0, 2.0, 3.0, 4.0]))
    assert buf._sum.sum() == pytest.approx(10.0)
    assert buf._min.min() == pytest.approx(1.0)


def test_nstep_writer_steady_state():
    buf = ReplayBuffer(100, 1, 1)
    w = NStepWriter(buf, n=3, gamma=0.9)
    obs = [np.array([float(i)]) for i in range(10)]
    for t in range(6):
        w.add(obs[t], np.array([0.0]), 1.0, obs[t + 1], terminated=False)
    # windows [0..2],[1..3],[2..4],[3..5] -> 4 emitted
    assert len(buf) == 4
    np.testing.assert_allclose(buf.reward[:4], 1 + 0.9 + 0.81, atol=1e-6)
    np.testing.assert_allclose(buf.discount[:4], 0.9**3, atol=1e-6)
    # s_{t+3} stored as next_obs
    np.testing.assert_allclose(buf.next_obs[0], [3.0])


def test_nstep_writer_termination_flush():
    buf = ReplayBuffer(100, 1, 1)
    w = NStepWriter(buf, n=3, gamma=0.5)
    o = [np.array([float(i)]) for i in range(4)]
    w.add(o[0], np.array([0.0]), 1.0, o[1], terminated=False)
    w.add(o[1], np.array([0.0]), 2.0, o[2], terminated=True)
    # Partial windows flush: [r0, r1] and [r1], both terminal (discount 0)
    assert len(buf) == 2
    np.testing.assert_allclose(sorted(buf.reward[:2]), [2.0, 1 + 0.5 * 2])
    np.testing.assert_allclose(buf.discount[:2], 0.0)


def test_nstep_writer_truncation_keeps_bootstrap():
    buf = ReplayBuffer(100, 1, 1)
    w = NStepWriter(buf, n=3, gamma=0.5)
    o = [np.array([float(i)]) for i in range(3)]
    w.add(o[0], np.array([0.0]), 1.0, o[1], terminated=False)
    w.add(o[1], np.array([0.0]), 2.0, o[2], terminated=False, truncated=True)
    assert len(buf) == 2
    # window [r0,r1]: m=2 discount 0.25; window [r1]: m=1 discount 0.5
    np.testing.assert_allclose(sorted(buf.discount[:2]), [0.25, 0.5])


def test_her_relabels_with_future_goals_and_own_actions():
    buf = ReplayBuffer(1000, 2, 1)  # obs = [x, goal]
    rng = np.random.default_rng(0)

    def reward_fn(achieved, goal):
        return 0.0 if abs(float(achieved[0] - goal[0])) < 0.5 else -1.0

    her = HindsightWriter(
        writer_factory=lambda: NStepWriter(buf, n=1, gamma=0.99),
        compute_reward=reward_fn,
        k_future=2,
        rng=rng,
    )
    # 1-D walk: position t -> t+1, desired goal 10 (never achieved)
    for t in range(5):
        her.add(
            observation=np.array([float(t)]),
            achieved_goal=np.array([float(t)]),
            desired_goal=np.array([10.0]),
            action=np.array([float(t)]),  # action == t so we can check pairing
            reward=-1.0,
            next_observation=np.array([float(t + 1)]),
            next_achieved_goal=np.array([float(t + 1)]),
            terminated=False,
        )
    n = her.end_episode(truncated=True)
    assert n >= 5 * 3  # may truncate relabeled episodes early at success
    data = buf.gather(np.arange(len(buf)))
    # Every stored transition's action matches its own obs x-coordinate
    # (the reference bug stored the final action everywhere).
    np.testing.assert_allclose(data["action"][:, 0], data["obs"][:, 0])
    # Some relabeled transitions achieved their substituted goal.
    assert np.any(data["reward"] == 0.0)
    # Original (goal=10) transitions are present too (quirk #14 fix).
    assert np.sum(data["obs"][:, 1] == 10.0) == 5


def test_linear_schedule_pure():
    assert linear_schedule(0, 10, 1.0, 0.0) == 1.0
    assert linear_schedule(5, 10, 1.0, 0.0) == 0.5
    assert linear_schedule(20, 10, 1.0, 0.0) == 0.0
    # calling twice does not change the result (no reference quirk #8)
    assert linear_schedule(5, 10, 1.0, 0.0) == 0.5


class TestReplaySnapshot:
    def test_uniform_roundtrip(self, tmp_path):
        from d4pg_tpu.replay import ReplayBuffer
        from d4pg_tpu.replay.uniform import Transition

        rng = np.random.default_rng(0)
        buf = ReplayBuffer(100, 4, 2)
        buf.add_batch(Transition(
            rng.normal(size=(30, 4)).astype(np.float32),
            rng.uniform(-1, 1, (30, 2)).astype(np.float32),
            rng.normal(size=30).astype(np.float32),
            rng.normal(size=(30, 4)).astype(np.float32),
            np.full(30, 0.99, np.float32)))
        path = str(tmp_path / "replay.npz")
        buf.snapshot(path)
        buf2 = ReplayBuffer(100, 4, 2)
        assert buf2.restore(path) == 30
        assert len(buf2) == 30
        got = buf2.gather(np.arange(30))
        want = buf.gather(np.arange(30))
        for k in got:
            np.testing.assert_array_equal(got[k], want[k])
        # ring continues correctly after restore
        buf2.add(np.zeros(4), np.zeros(2), 0.0, np.zeros(4), 0.99)
        assert len(buf2) == 31

    def test_per_roundtrip_preserves_priorities(self, tmp_path):
        from d4pg_tpu.replay import PrioritizedReplayBuffer
        from d4pg_tpu.replay.uniform import Transition

        rng = np.random.default_rng(1)
        buf = PrioritizedReplayBuffer(64, 3, 1, tree_backend="numpy")
        buf.add_batch(Transition(
            rng.normal(size=(20, 3)).astype(np.float32),
            rng.uniform(-1, 1, (20, 1)).astype(np.float32),
            rng.normal(size=20).astype(np.float32),
            rng.normal(size=(20, 3)).astype(np.float32),
            np.full(20, 0.99, np.float32)))
        buf.update_priorities(np.arange(20), rng.uniform(0.1, 5.0, 20))
        path = str(tmp_path / "replay.npz")
        buf.snapshot(path)
        buf2 = PrioritizedReplayBuffer(64, 3, 1, tree_backend="numpy")
        assert buf2.restore(path) == 20
        np.testing.assert_allclose(
            buf2._sum.get(np.arange(20)), buf._sum.get(np.arange(20)))
        assert buf2._max_priority == buf._max_priority
        # sampling statistics stay proportional after restore
        b = buf2.sample(16, np.random.default_rng(2), step=0)
        assert np.isfinite(b["weights"]).all()

    def test_restore_capacity_mismatch(self, tmp_path):
        from d4pg_tpu.replay import ReplayBuffer
        from d4pg_tpu.replay.uniform import Transition

        buf = ReplayBuffer(50, 2, 1)
        buf.add_batch(Transition(
            np.zeros((40, 2), np.float32), np.zeros((40, 1), np.float32),
            np.zeros(40, np.float32), np.zeros((40, 2), np.float32),
            np.ones(40, np.float32)))
        path = str(tmp_path / "replay.npz")
        buf.snapshot(path)
        small = ReplayBuffer(10, 2, 1)
        with pytest.raises(ValueError, match="capacity"):
            small.restore(path)

    def test_wrapped_ring_restores_write_head(self, tmp_path):
        from d4pg_tpu.replay import ReplayBuffer
        from d4pg_tpu.replay.uniform import Transition

        buf = ReplayBuffer(8, 2, 1)
        mk = lambda lo, hi: Transition(
            np.arange(lo, hi, dtype=np.float32).reshape(-1, 1).repeat(2, 1),
            np.zeros((hi - lo, 1), np.float32),
            np.arange(lo, hi, dtype=np.float32),
            np.zeros((hi - lo, 2), np.float32),
            np.ones(hi - lo, np.float32))
        buf.add_batch(mk(0, 11))  # wraps: pos = 3
        assert buf._pos == 3 and len(buf) == 8
        path = str(tmp_path / "r.npz")
        buf.snapshot(path)
        buf2 = ReplayBuffer(8, 2, 1)
        buf2.restore(path)
        assert buf2._pos == 3  # FIFO order resumes where it left off
        np.testing.assert_array_equal(buf2.reward, buf.reward)

    def test_snapshot_concurrent_with_writers(self, tmp_path):
        """Snapshot under concurrent add_batch never tears rows: every
        restored transition is internally consistent (obs embeds the same
        tag as its reward)."""
        import threading

        from d4pg_tpu.replay import PrioritizedReplayBuffer, ReplayBuffer
        from d4pg_tpu.replay.uniform import Transition

        buf = PrioritizedReplayBuffer(4096, 4, 1, tree_backend="numpy")

        stop = threading.Event()
        tag = [0]

        def writer():
            while not stop.is_set():
                t = tag[0]
                tag[0] += 1
                n = 32
                obs = np.full((n, 4), float(t), np.float32)
                buf.add_batch(
                    Transition(
                        obs,
                        np.zeros((n, 1), np.float32),
                        np.full(n, float(t), np.float32),  # reward == tag
                        obs,
                        np.ones(n, np.float32),
                    )
                )

        th = threading.Thread(target=writer, daemon=True)
        th.start()
        try:
            paths = []
            for i in range(5):
                p = str(tmp_path / f"snap{i}.npz")
                buf.snapshot(p)
                paths.append(p)
        finally:
            stop.set()
            th.join(timeout=10)
        for p in paths:
            b2 = PrioritizedReplayBuffer(4096, 4, 1, tree_backend="numpy")
            n = b2.restore(p)
            got = b2.gather(np.arange(n))
            # row consistency: all obs columns equal the row's reward tag
            np.testing.assert_array_equal(got["obs"], got["obs"][:, :1].repeat(4, 1))
            np.testing.assert_array_equal(got["obs"][:, 0], got["reward"])
            # priorities restored strictly positive (no min-tree poison)
            assert np.all(b2._sum.get(np.arange(n)) > 0)
