"""Round-7 host data-plane: native batched sample/gather/write-back,
zero-alloc staging, batched n-step ingest, per-stage telemetry.

Covers the oracle contracts the tentpole rests on:

- ``sample_block`` (native) ≡ the NumPy tree path: identical indices,
  gathered rows, IS weights, and generation stamps under a fixed seed;
- batched ``update_priorities`` ≡ NumPy semantics, including the
  generation-stamp drop of recycled slots and the max-priority reduce;
- ``tree_backend="auto"`` degrades to NumPy with no behavior change when
  the native build is unavailable (monkeypatched ``load_library`` failure);
- the ``sample_many``/``sample_block`` seeded RNG stream is a frozen
  determinism contract (PR 1 changed it once; this pins it);
- ``BatchedNStepWriter`` emits exactly what N sequential ``NStepWriter``s
  emit;
- a fresh checkout rebuilds ``libsumtree.so`` from source instead of
  loading a stale binary;
- ``StageTimers`` telemetry lands in a training run's metrics.jsonl.
"""

import os
import shutil

import numpy as np
import pytest

from d4pg_tpu.replay import (
    BatchedNStepWriter,
    MinTree,
    NStepWriter,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SumTree,
)
from d4pg_tpu.replay.uniform import Transition

native = pytest.importorskip("d4pg_tpu.replay.native")

try:
    native.load_library()
    HAVE_NATIVE = True
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not HAVE_NATIVE, reason="g++ build unavailable")


def _filled_pair(rows=200, capacity=256, obs_dim=3, act_dim=2, seed=0, **kw):
    """Two identically-filled PERs, NumPy oracle + native."""
    bufs = [
        PrioritizedReplayBuffer(capacity, obs_dim, act_dim, tree_backend=tb, **kw)
        for tb in ("numpy", "native")
    ]
    rng = np.random.default_rng(seed)
    t = Transition(
        rng.normal(size=(rows, obs_dim)).astype(np.float32),
        rng.uniform(-1, 1, (rows, act_dim)).astype(np.float32),
        rng.normal(size=rows).astype(np.float32),
        rng.normal(size=(rows, obs_dim)).astype(np.float32),
        np.full(rows, 0.99, np.float32),
    )
    pri = np.random.default_rng(seed + 1).uniform(0.05, 4.0, rows)
    for b in bufs:
        b.add_batch(t)
        b.update_priorities(np.arange(rows), pri)
    return bufs


@needs_native
def test_sample_block_native_matches_numpy_oracle():
    """The tentpole contract: one fused C call ≡ the NumPy path — same
    indices, same gathered rows, same IS weights, same generation stamps."""
    a, b = _filled_pair()
    for k, B, step in ((1, 32, 0), (4, 16, 7), (8, 8, 123)):
        ba = a.sample_block(B, k, np.random.default_rng(42), step=step)
        bb = b.sample_block(B, k, np.random.default_rng(42), step=step)
        np.testing.assert_array_equal(ba["indices"].idx, bb["indices"].idx)
        np.testing.assert_array_equal(ba["indices"].gen, bb["indices"].gen)
        np.testing.assert_array_equal(ba["weights"], bb["weights"])
        for key in ("obs", "action", "reward", "next_obs", "discount"):
            np.testing.assert_array_equal(ba[key], bb[key])


@needs_native
def test_update_priorities_native_matches_numpy_oracle():
    """Post-write-back tree mass (sum + min leaves) and max_priority agree,
    with duplicate indices and [K, B]-shaped inputs."""
    a, b = _filled_pair(seed=3)
    rng = np.random.default_rng(9)
    idx = rng.integers(0, 200, size=(4, 16))  # duplicates likely
    pri = rng.uniform(0.01, 7.0, size=(4, 16))
    a.update_priorities(idx, pri)
    b.update_priorities(idx, pri)
    leaves = np.arange(200)
    np.testing.assert_allclose(
        a._sum.get(leaves), b._sum.get(leaves), rtol=1e-12
    )
    assert a._min.min() == pytest.approx(b._min.min(), rel=1e-12)
    assert a._max_priority == pytest.approx(b._max_priority, rel=1e-12)


@needs_native
def test_update_priorities_native_generation_filter():
    """Write-backs for recycled slots are dropped natively, exactly as the
    NumPy SampledIndices path drops them."""
    a, b = _filled_pair(rows=8, capacity=8, obs_dim=1, act_dim=1, eps=0.0, alpha=1.0)
    sa = a.sample_block(4, 2, np.random.default_rng(0), step=0)
    sb = b.sample_block(4, 2, np.random.default_rng(0), step=0)
    # recycle the whole ring while the "dispatch" is in flight
    rng = np.random.default_rng(5)
    t = Transition(
        rng.normal(size=(8, 1)).astype(np.float32),
        rng.normal(size=(8, 1)).astype(np.float32),
        rng.normal(size=8).astype(np.float32),
        rng.normal(size=(8, 1)).astype(np.float32),
        np.full(8, 0.99, np.float32),
    )
    a.add_batch(t)
    b.add_batch(t)
    a.update_priorities(sa["indices"], np.full((2, 4), 1e-6))
    b.update_priorities(sb["indices"], np.full((2, 4), 1e-6))
    np.testing.assert_allclose(
        a._sum.get(np.arange(8)), b._sum.get(np.arange(8)), rtol=1e-12
    )
    # every update dropped → leaves still carry the fresh-insert seed
    np.testing.assert_allclose(
        b._sum.get(np.arange(8)), b._max_priority**b.alpha, rtol=1e-12
    )
    assert a._max_priority == b._max_priority


def test_sample_block_batches_equal_sample_many():
    """Dealt [K, B] block batch i ≡ sample_many's batch i (the round-robin
    stratification contract), on the NumPy path."""
    buf = _filled_pair()[0]
    K, B = 4, 16
    blk = buf.sample_block(B, K, np.random.default_rng(11), step=5)
    sm = buf.sample_many(B, K, np.random.default_rng(11), step=5)
    for i in range(K):
        np.testing.assert_array_equal(
            np.asarray(sm[i]["indices"].idx), blk["indices"].idx[i]
        )
        np.testing.assert_array_equal(sm[i]["weights"], blk["weights"][i])
        for key in ("obs", "action", "reward", "next_obs", "discount"):
            np.testing.assert_array_equal(sm[i][key], blk[key][i])


def test_sample_and_sample_block_k1_share_the_stream():
    """sample() and sample_block(B, 1) consume identical RNG state and
    return the same batch — the trainer's K=1 switch to the block path
    cannot move seeded runs."""
    buf = _filled_pair()[0]
    r1, r2 = np.random.default_rng(5), np.random.default_rng(5)
    s = buf.sample(16, r1, step=3)
    blk = buf.sample_block(16, 1, r2, step=3)
    assert r1.bit_generator.state == r2.bit_generator.state
    np.testing.assert_array_equal(np.asarray(s["indices"].idx), blk["indices"].idx[0])
    np.testing.assert_array_equal(s["weights"], blk["weights"][0])
    np.testing.assert_array_equal(s["obs"], blk["obs"][0])


@pytest.mark.parametrize("tree_backend", ["numpy"] + (["native"] if HAVE_NATIVE else []))
def test_seeded_draw_stream_contract(tree_backend):
    """The sample_many/sample_block RNG stream is a DETERMINISM CONTRACT
    (PR 1's K·B-wide descent changed seeded draws once; this freezes it):
    one Generator.uniform of size K·B over the equal-mass stratified
    bounds, low edge inclusive — nothing else may touch the stream.

    The frozen fixture: capacity 64, 40 uniform-priority inserts,
    sample_block(B=4, K=2, rng=default_rng(123), step=0).
    """
    buf = PrioritizedReplayBuffer(
        64, 1, 1, alpha=1.0, tree_backend=tree_backend
    )
    buf.add_batch(
        Transition(
            np.arange(40, dtype=np.float32)[:, None],
            np.zeros((40, 1), np.float32),
            np.zeros(40, np.float32),
            np.zeros((40, 1), np.float32),
            np.ones(40, np.float32),
        )
    )
    blk = buf.sample_block(4, 2, np.random.default_rng(123), step=0)
    # the documented recipe, reimplemented independently
    tree = SumTree(64)
    tree.set(np.arange(40), np.ones(40))
    total = tree.sum()
    bounds = np.linspace(0.0, total, 8 + 1)
    prefixes = np.random.default_rng(123).uniform(bounds[:-1], bounds[1:])
    prefixes = np.minimum(prefixes, np.nextafter(total, 0.0))
    expect = np.minimum(tree.find_prefixsum_idx(prefixes), 39)
    dealt = expect.reshape(4, 2).T  # draw j → block[j % K, j // K]
    np.testing.assert_array_equal(blk["indices"].idx, dealt)
    # the frozen literal — if this moves, seeded replays break: bump it
    # ONLY with a changelog entry declaring the stream change
    np.testing.assert_array_equal(
        blk["indices"].idx, [[3, 11, 20, 34], [5, 15, 29, 36]]
    )


def test_auto_backend_falls_back_to_numpy_without_gcc(monkeypatch):
    """tree_backend='auto' with a failing native build (no g++ / bad
    toolchain) must silently produce the NumPy path with identical
    sampling behavior — no crash anywhere in the block pipeline."""
    monkeypatch.setattr(
        native, "load_library",
        lambda: (_ for _ in ()).throw(RuntimeError("g++ not found")),
    )
    buf = PrioritizedReplayBuffer(64, 3, 2, tree_backend="auto")
    assert isinstance(buf._sum, SumTree) and isinstance(buf._min, MinTree)
    assert not buf._use_native
    rng = np.random.default_rng(0)
    for i in range(40):
        buf.add(rng.normal(size=3), rng.normal(size=2), float(i), rng.normal(size=3), 0.99)
    blk = buf.sample_block(8, 2, np.random.default_rng(1), step=0)
    assert blk["obs"].shape == (2, 8, 3)
    buf.update_priorities(blk["indices"], np.abs(rng.normal(size=(2, 8))) + 0.1)
    # oracle equivalence of the fallback: same numbers as an explicit numpy buffer
    ref = PrioritizedReplayBuffer(64, 3, 2, tree_backend="numpy")
    rng = np.random.default_rng(0)
    for i in range(40):
        ref.add(rng.normal(size=3), rng.normal(size=2), float(i), rng.normal(size=3), 0.99)
    b2 = ref.sample_block(8, 2, np.random.default_rng(1), step=0)
    np.testing.assert_array_equal(blk["indices"].idx, b2["indices"].idx)
    np.testing.assert_array_equal(blk["obs"], b2["obs"])


@needs_native
def test_fresh_checkout_rebuilds_stale_so(tmp_path, monkeypatch):
    """A clean checkout can leave libsumtree.so with mtime == source (or a
    foreign/corrupt binary entirely): load_library must REBUILD from source
    rather than dlopen the stale file — dlopening this garbage would raise."""
    src = tmp_path / "sumtree.cpp"
    shutil.copy(native._source_path(), src)
    bdir = tmp_path / "build"
    bdir.mkdir()
    so = bdir / "libsumtree.so"
    so.write_bytes(b"definitely not an ELF shared object")
    t = os.stat(src).st_mtime
    os.utime(so, (t, t))  # equal mtimes — the fresh-checkout signature
    monkeypatch.setattr(native, "_source_path", lambda: str(src))
    monkeypatch.setattr(native, "_build_dir", lambda: str(bdir))
    monkeypatch.setattr(native, "_LIB", None)  # restored after the test
    lib = native.load_library()
    assert lib.st_root is not None
    assert so.stat().st_size > 1000  # the garbage file was replaced


class TestBatchedNStepWriter:
    def _run_pair(self, N, n, gamma, T, seed=0, term_steps=(), trunc_steps=()):
        rng = np.random.default_rng(seed)
        term = np.zeros((T, N), bool)
        trunc = np.zeros((T, N), bool)
        for t, i in term_steps:
            term[t, i] = True
        for t, i in trunc_steps:
            trunc[t, i] = True
        # distinct obs per (actor, step) so rows are identifiable
        obs = (
            np.arange(N)[None, :, None] * 1000.0
            + np.arange(T + 1)[:, None, None]
            + np.zeros((1, 1, 2))
        ).astype(np.float32)
        act = rng.uniform(-1, 1, (T, N, 1)).astype(np.float32)
        rew = rng.normal(size=(T, N))
        seq = ReplayBuffer(4096, 2, 1)
        writers = [NStepWriter(seq, n, gamma) for _ in range(N)]
        bat = ReplayBuffer(4096, 2, 1)
        bw = BatchedNStepWriter(bat, N, n, gamma)
        for t in range(T):
            for i in range(N):
                writers[i].add(
                    obs[t, i], act[t, i], float(rew[t, i]), obs[t + 1, i],
                    terminated=bool(term[t, i]), truncated=bool(trunc[t, i]),
                )
            bw.add_batch(obs[t], act[t], rew[t], obs[t + 1], term[t], trunc[t])
        return seq, bat

    @staticmethod
    def _rows(buf):
        g = buf.gather(np.arange(len(buf)))
        m = np.concatenate(
            [g["obs"], g["action"], g["reward"][:, None], g["next_obs"],
             g["discount"][:, None]], axis=1,
        )
        return m[np.lexsort(m.T)]

    def test_matches_sequential_writers_with_episode_ends(self):
        """Content parity (as row sets — only cross-actor insertion order
        may differ) through terminations, truncations, and partial-window
        flushes."""
        seq, bat = self._run_pair(
            N=3, n=4, gamma=0.9, T=50,
            term_steps=((7, 0), (20, 2), (41, 1)),
            trunc_steps=((13, 1), (33, 0), (44, 2)),
        )
        assert len(seq) == len(bat) > 0
        np.testing.assert_array_equal(self._rows(seq), self._rows(bat))

    def test_steady_state_identical_and_ordered(self):
        """No episode ends: byte-identical buffers INCLUDING ring order
        (the fast path emits in actor order, like the sequential loop)."""
        seq, bat = self._run_pair(N=4, n=3, gamma=0.8, T=20)
        assert len(seq) == len(bat) == 4 * (20 - 3 + 1)
        ga = seq.gather(np.arange(len(seq)))
        gb = bat.gather(np.arange(len(bat)))
        for key in ga:
            np.testing.assert_array_equal(ga[key], gb[key])

    def test_n1_every_step_emits(self):
        seq, bat = self._run_pair(N=2, n=1, gamma=0.99, T=10, term_steps=((4, 0),))
        assert len(bat) == 20
        np.testing.assert_array_equal(self._rows(seq), self._rows(bat))

    def test_reset_drops_windows(self):
        buf = ReplayBuffer(64, 1, 1)
        bw = BatchedNStepWriter(buf, 2, 3, 0.9)
        o = np.zeros((2, 1), np.float32)
        a = np.zeros((2, 1), np.float32)
        bw.add_batch(o, a, np.ones(2), o, np.zeros(2, bool), np.zeros(2, bool))
        bw.reset()
        bw.add_batch(o, a, np.ones(2), o, np.zeros(2, bool), np.ones(2, bool))
        # post-reset: only the single fresh step flushes (m=1 windows)
        assert len(buf) == 2
        np.testing.assert_allclose(buf.discount[:2], 0.9)

    def test_drop_actor_plus_mask_matches_scalar_restart(self):
        """Supervised-pool recovery semantics: on a worker failure the
        actor's in-flight window is dropped WHOLE (drop_actor) and its
        rows are masked (active=) until it rejoins — content-identical to
        a scalar NStepWriter that resets at the failure and is fed only
        the post-restart subsequence. No torn transition reaches replay."""
        N, n, gamma, T = 2, 3, 0.9, 12
        down = range(4, 7)  # actor 1 dark on these steps
        rng = np.random.default_rng(5)
        obs = (
            np.arange(N)[None, :, None] * 1000.0
            + np.arange(T + 1)[:, None, None]
            + np.zeros((1, 1, 2))
        ).astype(np.float32)
        act = rng.uniform(-1, 1, (T, N, 1)).astype(np.float32)
        rew = rng.normal(size=(T, N))
        seq = ReplayBuffer(4096, 2, 1)
        writers = [NStepWriter(seq, n, gamma) for _ in range(N)]
        bat = ReplayBuffer(4096, 2, 1)
        bw = BatchedNStepWriter(bat, N, n, gamma)
        zeros = np.zeros(N, bool)
        mask = np.array([True, False])
        for t in range(T):
            if t == min(down):  # the failure instant
                bw.drop_actor(1)
                writers[1].reset()
            live = mask if t in down else None
            bw.add_batch(obs[t], act[t], rew[t], obs[t + 1], zeros, zeros,
                         active=live)
            for i in range(N):
                if live is not None and not live[i]:
                    continue
                writers[i].add(
                    obs[t, i], act[t, i], float(rew[t, i]), obs[t + 1, i],
                    terminated=False, truncated=False,
                )
        assert len(seq) == len(bat) > 0
        np.testing.assert_array_equal(self._rows(seq), self._rows(bat))

    def test_masked_add_with_episode_ends_matches(self):
        """Mask + termination on the SAME step (the surviving actor's
        episode ends while another is down) takes the degraded path —
        emission must still match the scalar writers."""
        N, n, gamma = 3, 3, 0.8
        rng = np.random.default_rng(9)
        seq = ReplayBuffer(4096, 2, 1)
        writers = [NStepWriter(seq, n, gamma) for _ in range(N)]
        bat = ReplayBuffer(4096, 2, 1)
        bw = BatchedNStepWriter(bat, N, n, gamma)
        mask = np.array([True, False, True])
        for t in range(8):
            obs = rng.normal(size=(N, 2)).astype(np.float32)
            nxt = rng.normal(size=(N, 2)).astype(np.float32)
            a = rng.uniform(-1, 1, (N, 1)).astype(np.float32)
            r = rng.normal(size=N)
            term = np.array([t == 5, False, False])
            live = mask if t in (4, 5) else None
            bw.add_batch(obs, a, r, nxt, term, np.zeros(N, bool), active=live)
            for i in range(N):
                if live is not None and not live[i]:
                    continue
                writers[i].add(obs[i], a[i], float(r[i]), nxt[i],
                               terminated=bool(term[i]), truncated=False)
        assert len(seq) == len(bat) > 0
        np.testing.assert_array_equal(self._rows(seq), self._rows(bat))


def test_stage_timers_accumulate_and_report():
    from d4pg_tpu.utils.profiling import StageTimers

    t = StageTimers(annotate_prefix=None)
    with t.stage("sample"):
        pass
    with t.stage("sample"):
        pass
    with t.stage("h2d_stage"):
        pass
    s = t.scalars()
    assert s["stage_sample_calls"] == 2.0 and s["stage_sample_s"] >= 0.0
    assert s["stage_h2d_stage_calls"] == 1.0
    ms = t.summary_ms(per=2)
    assert set(ms) == {"sample", "h2d_stage"}
    t.reset()
    assert t.scalars() == {}


@pytest.mark.parametrize("steps_per_dispatch", [1, 2])
def test_trainer_writes_stage_telemetry(tmp_path, steps_per_dispatch):
    """A training run's metrics.jsonl rows carry the per-stage counters —
    the telemetry half of the tentpole, end to end through the trainer."""
    import json

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = TrainConfig(
        env="pendulum",
        total_steps=2 * steps_per_dispatch,
        warmup_steps=32,
        batch_size=16,
        num_envs=2,
        eval_interval=steps_per_dispatch,
        checkpoint_interval=10**6,
        steps_per_dispatch=steps_per_dispatch,
        log_dir=str(tmp_path / "run"),
        agent=D4PGConfig(hidden_sizes=(16, 16)),
    )
    t = Trainer(apply_env_preset(cfg))
    try:
        t.train()
    finally:
        t.close()
    rows = [
        json.loads(line)
        for line in open(tmp_path / "run" / "metrics.jsonl")
    ]
    last = rows[-1]
    for stage in (
        "env_step", "replay_insert", "sample", "h2d_stage", "train_dispatch",
        "priority_writeback",
    ):
        assert last[f"stage_{stage}_s"] >= 0.0, stage
        assert last[f"stage_{stage}_calls"] >= 1.0, stage
    # dispatch accounting: one train_dispatch per K-step dispatch
    assert last["stage_train_dispatch_calls"] == 2.0
