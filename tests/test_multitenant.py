"""Multi-tenant serving (ISSUE 12): the versioned request frame
(policy-id + QoS + tenant), the multi-policy PolicyServer, the router's
per-tenant quotas + class-aware shed, and the per-policy canary
machinery's isolation contract.

Protocol backward compat is pinned at the BYTE level: a PR-8-era client
(v1 frames, no policy-id field) against the new server must see
byte-identical replies, and a new client against an old server must fail
loudly with a clear protocol-version error, never a decode crash.
"""

import os
import socket
import struct
import threading
import time

import jax
import numpy as np
import pytest

from d4pg_tpu.agent import act_deterministic
from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.serve import PolicyBundle, PolicyClient, PolicyServer, Router
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.bundle import actor_template, export_bundle, load_bundle
from d4pg_tpu.serve.client import Overloaded, ServerError
from d4pg_tpu.serve.protocol import ProtocolError

CFG = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(8, 8))
CFG_ALT = D4PGConfig(obs_dim=3, action_dim=2, hidden_sizes=(8, 8))
OBS = np.array([0.1, -0.2, 0.05, 0.3], np.float32)
OBS_ALT = np.array([0.1, -0.2, 0.05], np.float32)
PARAMS = actor_template(CFG)
PARAMS_ALT = actor_template(CFG_ALT)


def _bundle(config=CFG, params=None, path=None):
    return PolicyBundle(
        config=config,
        actor_params=params if params is not None else (
            PARAMS if config is CFG else PARAMS_ALT
        ),
        action_low=np.full(2, -1.0, np.float32),
        action_high=np.full(2, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "test"},
        path=path,
    )


def _ref(params, obs=OBS, config=CFG):
    return np.clip(
        np.asarray(act_deterministic(config, params, obs[None])[0]), -1.0, 1.0
    )


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _server(bundle=None, policies=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_wait_us", 200)
    kw.setdefault("watch_bundle", False)
    srv = PolicyServer(
        bundle if bundle is not None else _bundle(),
        port=0,
        policies=policies,
        **kw,
    )
    srv.start()
    return srv


# ------------------------------------------------------------ wire codec
def test_act2_codec_roundtrip():
    payload = protocol.encode_act2(
        OBS, 12345, policy_id="alt", qos=protocol.QOS_BULK, tenant="team-a"
    )
    obs, deadline, pid, qos, tenant = protocol.decode_act2(payload)
    np.testing.assert_array_equal(obs, OBS)
    assert (deadline, pid, qos, tenant) == (12345, "alt", protocol.QOS_BULK,
                                            "team-a")
    # empty ids fall back to the default policy / anonymous tenant
    obs, _, pid, qos, tenant = protocol.decode_act2(
        protocol.encode_act2(OBS)
    )
    assert pid == protocol.DEFAULT_POLICY and tenant == ""
    assert qos == protocol.QOS_INTERACTIVE


def test_act2_codec_rejects_malformed():
    with pytest.raises(ProtocolError, match="qos"):
        protocol.encode_act2(OBS, qos=7)
    with pytest.raises(ProtocolError, match="header"):
        protocol.decode_act2(b"\x00\x01")
    good = protocol.encode_act2(OBS, policy_id="alt")
    with pytest.raises(ProtocolError, match="qos"):
        protocol.decode_act2(b"\x09" + good[1:])
    with pytest.raises(ProtocolError, match="float32"):
        protocol.decode_act2(good[:-2])
    with pytest.raises(ProtocolError, match="declare"):
        # policy_len says 200 bytes but the payload ends first
        protocol.decode_act2(struct.pack("<BBBBI", 0, 200, 0, 0, 0) + b"abc")


def test_act2_frames_carry_version_2_plain_frames_version_1():
    """The per-type frame-version floor: only ACT2 advertises v2, so the
    whole v1 sublanguage stays byte-compatible with PR-8 peers."""
    a, b = socket.socketpair()
    try:
        protocol.write_frame(a, protocol.ACT, 1, protocol.encode_act(OBS))
        hdr = b.recv(protocol.HEADER.size, socket.MSG_WAITALL)
        assert protocol.HEADER.unpack(hdr)[1] == 1  # version byte
        b.recv(1 << 16)
        protocol.write_frame(a, protocol.ACT2, 2, protocol.encode_act2(OBS))
        hdr = b.recv(protocol.HEADER.size, socket.MSG_WAITALL)
        assert protocol.HEADER.unpack(hdr)[1] == 2
    finally:
        a.close()
        b.close()


# ------------------------------------------------- backward-compat pins
def _raw_v1_act(port, obs, req_id=7):
    """A PR-8-era client, byte for byte: version-1 header, ACT payload =
    deadline u32 + obs f32s. Returns the raw reply frame bytes."""
    payload = struct.pack("<I", 0) + np.asarray(obs, np.float32).tobytes()
    frame = protocol.HEADER.pack(
        protocol.MAGIC, 1, protocol.ACT, req_id, len(payload)
    ) + payload
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(frame)
        s.settimeout(10)
        hdr = s.recv(protocol.HEADER.size, socket.MSG_WAITALL)
        magic, version, msg_type, rid, length = protocol.HEADER.unpack(hdr)
        body = s.recv(length, socket.MSG_WAITALL) if length else b""
    return hdr + body, (magic, version, msg_type, rid, body)


def test_old_client_gets_default_policy_with_identical_reply_bytes():
    """The hard compat requirement: a v1 client against the multi-policy
    server lands on the DEFAULT policy and its reply frame is the exact
    byte sequence a PR-8 server would have produced — version byte 1,
    ACT_OK, echoed req_id, the default policy's action as f32s."""
    srv = _server(policies={"alt": _bundle(CFG_ALT)})
    try:
        raw, (magic, version, msg_type, rid, body) = _raw_v1_act(
            srv.port, OBS, req_id=42
        )
        assert magic == protocol.MAGIC and version == 1
        assert msg_type == protocol.ACT_OK and rid == 42
        # the default policy's action, as served to a CURRENT client over
        # the same wire — the old client's frame must be the same bytes
        # modulo the echoed req_id (version byte 1 included)
        with PolicyClient("127.0.0.1", srv.port) as c:
            served = c.act(OBS)
        np.testing.assert_allclose(served, _ref(PARAMS), rtol=1e-5, atol=1e-6)
        expected = protocol.HEADER.pack(
            protocol.MAGIC, 1, protocol.ACT_OK, 42, len(body)
        ) + protocol.encode_action(served)
        assert raw == expected  # byte-for-byte the PR-8 reply
    finally:
        srv.drain()


def _old_server(port_box, stop):
    """A PR-8-era server's read side, faithfully: version != 1 raises the
    protocol error, answered ERROR + close — the behavior a new client
    must surface as a clear version error."""
    lsock = socket.create_server(("127.0.0.1", 0))
    port_box.append(lsock.getsockname()[1])
    lsock.settimeout(30)
    try:
        conn, _ = lsock.accept()
        with conn:
            hdr = conn.recv(protocol.HEADER.size, socket.MSG_WAITALL)
            magic, version, msg_type, req_id, length = (
                protocol.HEADER.unpack(hdr)
            )
            if length:
                conn.recv(length, socket.MSG_WAITALL)
            if version != 1:
                msg = f"protocol version {version} (this server speaks 1)"
                conn.sendall(protocol.HEADER.pack(
                    protocol.MAGIC, 1, protocol.ERROR, 0, len(msg)
                ) + msg.encode())
    finally:
        lsock.close()
        stop.set()


def test_new_client_against_old_server_fails_with_clear_version_error():
    port_box, stop = [], threading.Event()
    t = threading.Thread(
        target=_old_server, args=(port_box, stop),
        name="old-server", daemon=True,
    )
    t.start()
    _wait(lambda: port_box, msg="old server port")
    with PolicyClient("127.0.0.1", port_box[0], timeout=10) as c:
        with pytest.raises(ServerError, match="protocol version"):
            c.act(OBS, policy_id="alt")
    t.join(timeout=10)


# ------------------------------------------------- multi-policy server
def test_server_routes_policies_independently():
    """Two resident policies with different shapes and params: each ACT2
    lands on ITS policy's batcher, v1 ACT lands on the default, and the
    per-policy healthz rows carry independent stats."""
    srv = _server(policies={"alt": _bundle(CFG_ALT)})
    try:
        with PolicyClient("127.0.0.1", srv.port) as c:
            np.testing.assert_allclose(
                c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                c.act(OBS_ALT, policy_id="alt"),
                _ref(PARAMS_ALT, OBS_ALT, CFG_ALT),
                rtol=1e-5, atol=1e-6,
            )
            # unknown policy: per-request ERROR, the connection SURVIVES
            with pytest.raises(ServerError, match="unknown policy"):
                c.act(OBS, policy_id="nope")
            # wrong obs dim for a resident policy: same contract
            with pytest.raises(ServerError, match="wants 3"):
                c.act(OBS, policy_id="alt")
            np.testing.assert_allclose(
                c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
            )
            h = c.healthz()
        rows = h["policies"]
        assert set(rows) == {"default", "alt"}
        assert rows["default"]["obs_dim"] == 4 and rows["alt"]["obs_dim"] == 3
        assert rows["default"]["replies_ok"] == 2
        assert rows["alt"]["replies_ok"] == 1
        assert h["unknown_policy"] == 1
        # aggregate compile_count sums every policy's bucket programs
        assert h["compile_count"] == sum(
            len(p.batcher.buckets) for p in srv._policies.values()
        )
    finally:
        srv.drain()


def test_per_policy_hot_reload_is_isolated(tmp_path):
    """Re-exporting policy B's bundle reloads B only: A's params_reloads
    stays 0, A's serving params unchanged, and only B's version vector
    (policies row bundle_mtime) advances."""
    d_def = str(tmp_path / "def")
    d_alt = str(tmp_path / "alt")
    export_bundle(d_def, CFG, PARAMS)
    export_bundle(d_alt, CFG_ALT, PARAMS_ALT)
    srv = _server(
        bundle=load_bundle(d_def),
        policies={"alt": load_bundle(d_alt)},
        watch_bundle=True,
    )
    try:
        before = srv.healthz()["policies"]
        new_alt = jax.tree_util.tree_map(lambda x: x + 0.5, PARAMS_ALT)
        time.sleep(0.05)  # ensure a distinct mtime
        export_bundle(d_alt, CFG_ALT, new_alt)
        assert srv.check_reload() is True
        h = srv.healthz()["policies"]
        assert h["alt"]["params_reloads"] == 1
        assert h["default"]["params_reloads"] == 0
        assert h["alt"]["bundle_mtime"] != before["alt"]["bundle_mtime"]
        assert h["default"]["bundle_mtime"] == before["default"]["bundle_mtime"]
        with PolicyClient("127.0.0.1", srv.port) as c:
            np.testing.assert_allclose(
                c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                c.act(OBS_ALT, policy_id="alt"),
                _ref(new_alt, OBS_ALT, CFG_ALT),
                rtol=1e-5, atol=1e-6,
            )
    finally:
        srv.drain()


# ---------------------------------------------------- router admission
def _router(servers, **kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("readmit_after", 1)
    r = Router([("127.0.0.1", s.port) for s in servers], port=0, **kw)
    r.start()
    r.wait_for_replicas(len(servers), timeout_s=60)
    return r


def test_tenant_quota_sheds_with_exact_per_tenant_identity():
    srv = _server()
    router = _router([srv], tenant_quotas={"greedy": (1.0, 2.0)})
    try:
        with PolicyClient("127.0.0.1", router.port) as c:
            outcomes = {"ok": 0, "quota": 0}
            for _ in range(6):
                try:
                    c.act(OBS, tenant="greedy")
                    outcomes["ok"] += 1
                except Overloaded as e:
                    assert str(e) == "quota"
                    outcomes["quota"] += 1
            for _ in range(3):
                c.act(OBS, tenant="modest")  # untouched by greedy's bucket
            h = c.healthz()
        assert outcomes["quota"] >= 1 and outcomes["ok"] >= 2, outcomes
        rows = h["tenants"]
        assert rows["greedy/interactive"]["overloaded"] == outcomes["quota"]
        assert rows["modest/interactive"] == {
            "requests": 3, "ok": 3, "overloaded": 0, "error": 0, "answered": 3,
        }
        for key, row in rows.items():
            assert row["requests"] == row["answered"], (key, row)
        assert h["requests_total"] == h["answered_total"]
        assert h["shed_quota"] == outcomes["quota"]
    finally:
        router.drain()
        srv.drain()


def test_bulk_sheds_first_interactive_admitted_to_capacity():
    """The shed-ordering contract, driven through the REAL wiring: pin
    fleet inflight above the bulk line but below capacity — bulk sheds
    ``bulk_capacity`` while interactive is still admitted; above
    capacity, interactive sheds ``capacity`` too."""
    srv = _server()
    router = _router([srv], replica_capacity=10, bulk_fraction=0.5)
    try:
        rep = router._replicas[0]
        with PolicyClient("127.0.0.1", router.port) as c:
            with router._lock:
                rep.inflight += 6          # between bulk line (5) and cap
            try:
                with pytest.raises(Overloaded, match="bulk_capacity"):
                    c.act(OBS, qos="bulk", tenant="batch")
                c.act(OBS, tenant="web")   # interactive still admitted
                with router._lock:
                    rep.inflight += 4      # now at capacity (10)
                with pytest.raises(Overloaded, match="capacity"):
                    c.act(OBS, tenant="web")
            finally:
                with router._lock:
                    rep.inflight -= 10
            c.act(OBS, qos="bulk", tenant="batch")  # admitted again
            h = c.healthz()
        assert h["shed_bulk_capacity"] == 1 and h["shed_capacity"] == 1
        assert h["capacity"]["total"] == 10 and h["capacity"]["bulk_limit"] == 5
        for key, row in h["tenants"].items():
            assert row["requests"] == row["answered"], (key, row)
    finally:
        router.drain()
        srv.drain()


def test_router_routes_policy_to_hosting_replicas_only():
    """Replica 0 hosts default only; replica 1 hosts default+alt: every
    alt request lands on replica 1, default traffic spreads."""
    s0 = _server()
    s1 = _server(policies={"alt": _bundle(CFG_ALT)})
    router = _router([s0, s1])
    try:
        _wait(
            lambda: "alt" in router._obs_dims,
            msg="router learns the alt policy from probes",
        )
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(6):
                np.testing.assert_allclose(
                    c.act(OBS_ALT, policy_id="alt"),
                    _ref(PARAMS_ALT, OBS_ALT, CFG_ALT),
                    rtol=1e-5, atol=1e-6,
                )
            for _ in range(6):
                c.act(OBS)
            h = c.healthz()
        assert s1.healthz()["policies"]["alt"]["replies_ok"] == 6
        assert h["replicas"][0]["policies"] == ["default"]
        assert sorted(h["replicas"][1]["policies"]) == ["alt", "default"]
        # default traffic used both replicas
        assert all(r["ok"] >= 3 for r in h["replicas"]), h["replicas"]
    finally:
        router.drain()
        s0.drain()
        s1.drain()


def test_tenant_flood_chaos_injects_identity_accounted_burst():
    from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

    inj = ChaosInjector(ChaosPlan.parse("tenant_flood@2:bulky"))
    srv = _server()
    router = _router(
        [srv],
        chaos=inj,
        tenant_quotas={"bulky": (2.0, 4.0)},
        flood_burst=25,
    )
    try:
        with PolicyClient("127.0.0.1", router.port) as c:
            c.act(OBS, tenant="web")
            c.act(OBS, tenant="web")  # 2nd request fires the flood
            assert inj.injections_total == 1
            _wait(
                lambda: (
                    lambda h: h["requests_total"] == h["answered_total"]
                    and h["requests_total"] >= 27
                )(router.healthz()),
                msg="flood answered",
            )
            h = c.healthz()
        row = h["tenants"]["bulky/bulk"]
        assert row["requests"] == 25 == row["answered"]
        # the quota absorbed most of the burst before any replica saw it
        assert row["overloaded"] >= 20, row
        assert any(
            e["event"] == "chaos_tenant_flood" for e in h["events_tail"]
        )
    finally:
        router.drain()
        srv.drain()


# -------------------------------------------- per-policy canary isolation
def _two_policy_fleet(tmp_path, canary_policy="alt", chaos=None, **router_kw):
    """Two replicas, each serving default+alt from their OWN bundle dirs,
    plus a canary source for one policy."""
    dirs = []
    servers = []
    for i in range(2):
        d_def = str(tmp_path / f"r{i}_def")
        d_alt = str(tmp_path / f"r{i}_alt")
        export_bundle(d_def, CFG, PARAMS)
        export_bundle(d_alt, CFG_ALT, PARAMS_ALT)
        dirs.append({"default": d_def, "alt": d_alt})
        srv = _server(
            bundle=load_bundle(d_def),
            policies={"alt": load_bundle(d_alt)},
            watch_bundle=True,
            poll_interval_s=0.05,
        )
        servers.append(srv)
    canary_dir = str(tmp_path / "canary")
    cfg = CFG_ALT if canary_policy == "alt" else CFG
    base = PARAMS_ALT if canary_policy == "alt" else PARAMS
    new_params = jax.tree_util.tree_map(lambda x: x + 0.5, base)
    export_bundle(canary_dir, cfg, new_params)
    router = Router(
        [("127.0.0.1", s.port) for s in servers],
        port=0,
        bundle_dirs=dirs,
        probe_interval_s=0.05,
        probe_timeout_s=1.0,
        readmit_after=2,
        canary_bundle={canary_policy: canary_dir},
        canary_fraction=0.5,
        canary_min_samples=5,
        canary_window=64,
        canary_attest_timeout_s=20.0,
        chaos=chaos,
        **router_kw,
    )
    router.start()
    router.wait_for_replicas(2, timeout_s=60)
    return servers, router, dirs, new_params


def test_per_policy_canary_promotes_without_touching_other_policy(tmp_path):
    servers, router, dirs, new_alt = _two_policy_fleet(tmp_path)
    try:
        state = lambda: router.healthz()["rollouts"]["alt"]["state"]  # noqa: E731
        _wait(lambda: state() != "idle", msg="alt rollout start")
        ref_old = _ref(PARAMS_ALT, OBS_ALT, CFG_ALT)
        ref_new = _ref(new_alt, OBS_ALT, CFG_ALT)
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(600):
                a = c.act(OBS_ALT, policy_id="alt", timeout=30)
                assert np.allclose(a, ref_old, atol=1e-5) or np.allclose(
                    a, ref_new, atol=1e-5
                ), a
                # default-policy traffic interleaves and must NEVER see
                # anything but the default params
                np.testing.assert_allclose(
                    c.act(OBS, timeout=30), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
                if state() == "idle":
                    break
                time.sleep(0.01)
            _wait(lambda: state() == "idle", msg="alt rollout settle")
            h = c.healthz()
        assert h["canary_promotions"] == 1 and h["canary_rollbacks"] == 0
        # the back-compat "canary" view is the DEFAULT policy's rollout —
        # no default rollout configured, so it reads idle throughout
        assert h["canary"]["state"] == "idle"
        # THE isolation pin: no replica ever reloaded the OTHER policy
        for s in servers:
            rows = s.healthz()["policies"]
            assert rows["default"]["params_reloads"] == 0
            assert rows["alt"]["params_reloads"] >= 1
    finally:
        router.drain()
        for s in servers:
            s.drain()


def test_per_policy_canary_rollback_leaves_other_policy_untouched(tmp_path):
    from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

    inj = ChaosInjector(ChaosPlan.parse("canary_corrupt@1"))
    servers, router, dirs, _new = _two_policy_fleet(tmp_path, chaos=inj)
    try:
        _wait(
            lambda: router.stats.canary_rollbacks >= 1,
            msg="auto-rollback on corrupt alt canary",
        )
        _wait(
            lambda: (
                lambda h: h["rollouts"]["alt"]["state"] == "idle"
                and h["admitted"] == 2
            )(router.healthz()),
            msg="rollback settle + re-admission",
        )
        # the acceptance pin: a rollback on policy alt leaves every other
        # policy's replicas with params_reloads == 0
        for s in servers:
            rows = s.healthz()["policies"]
            assert rows["default"]["params_reloads"] == 0
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(4):
                np.testing.assert_allclose(
                    c.act(OBS_ALT, policy_id="alt"),
                    _ref(PARAMS_ALT, OBS_ALT, CFG_ALT),
                    rtol=1e-5, atol=1e-6,
                )
                np.testing.assert_allclose(
                    c.act(OBS), _ref(PARAMS), rtol=1e-5, atol=1e-6
                )
    finally:
        router.drain()
        for s in servers:
            s.drain()


# ------------------------------------------------- elastic fleet seams
def test_add_and_remove_backend_at_runtime():
    s0 = _server()
    router = _router([s0])
    s1 = _server()
    try:
        idx = router.add_backend("127.0.0.1", s1.port)
        _wait(lambda: router.healthz()["admitted"] == 2, msg="admission")
        with PolicyClient("127.0.0.1", router.port) as c:
            for _ in range(8):
                c.act(OBS)
            h = c.healthz()
            assert all(r["ok"] >= 2 for r in h["replicas"]), h["replicas"]
            router.remove_backend(idx)
            assert router.healthz()["admitted"] == 1
            for _ in range(4):
                c.act(OBS)  # the survivor keeps serving
            h = c.healthz()
        assert h["replicas"][idx]["removed"] is True
        assert h["requests_total"] == h["answered_total"]
    finally:
        router.drain()
        s0.drain()
        s1.drain()


def test_scaledown_mid_canary_aborts_cleanly_never_strands(tmp_path):
    """THE scale-down chaos contract: removing the canary replica while
    its rollout is live must abort the rollout through the normal
    rollback — its bundle dir is RESTORED to the old content (nothing
    half-deployed survives on disk), the other replica is untouched, and
    the rollout machine returns to idle (no stuck gates)."""
    servers, router, dirs, _new = _two_policy_fleet(tmp_path)
    try:
        _wait(
            lambda: router.healthz()["rollouts"]["alt"]["state"] != "idle",
            msg="rollout start",
        )
        # the canary is the highest-index eligible replica: replica 1
        canary_idx = 1
        _wait(
            lambda: "alt" in router._replicas[canary_idx].canary_for,
            msg="canary marked",
        )
        old_doc = open(os.path.join(dirs[canary_idx]["alt"], "bundle.json")).read()
        # scale-down: drain the process, then deregister (the autoscaler's
        # exact call order)
        servers[canary_idx].drain()
        router.remove_backend(canary_idx)
        _wait(
            lambda: router.healthz()["rollouts"]["alt"]["state"] == "idle",
            msg="rollout aborted/settled after scale-down",
        )
        h = router.healthz()
        assert h["canary_rollbacks"] >= 1 and h["canary_promotions"] == 0
        assert not router._readmit_gate, router._readmit_gate
        # the removed replica's bundle dir was restored — byte-identical
        # json to the pre-rollout bundle, loadable params
        restored = open(
            os.path.join(dirs[canary_idx]["alt"], "bundle.json")
        ).read()
        assert restored == old_doc
        load_bundle(dirs[canary_idx]["alt"])  # params + json consistent
        # the surviving replica never reloaded anything
        rows = servers[0].healthz()["policies"]
        assert rows["alt"]["params_reloads"] == 0
        assert rows["default"]["params_reloads"] == 0
        # and the fleet still serves both policies
        with PolicyClient("127.0.0.1", router.port) as c:
            c.act(OBS)
            c.act(OBS_ALT, policy_id="alt")
    finally:
        router.drain()
        servers[0].drain()
