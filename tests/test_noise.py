"""Golden/statistical tests for noise processes under fixed PRNG keys."""

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.ops import (
    gaussian_noise_init,
    gaussian_noise_reset,
    gaussian_noise_sample,
    ou_noise_init,
    ou_noise_reset,
    ou_noise_sample,
)


def test_gaussian_scale_and_decay():
    state = gaussian_noise_init(epsilon=0.3)
    key = jax.random.PRNGKey(0)
    samples = gaussian_noise_sample(state, key, (10000,), sigma=1.0)
    assert abs(float(jnp.std(samples)) - 0.3) < 0.01
    for _ in range(100):
        state = gaussian_noise_reset(state, decay=0.01)
    assert abs(float(state.epsilon) - 0.3 * 0.99**100) < 1e-5


def test_gaussian_deterministic_under_key():
    state = gaussian_noise_init()
    key = jax.random.PRNGKey(42)
    a = gaussian_noise_sample(state, key, (5,))
    b = gaussian_noise_sample(state, key, (5,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ou_mean_reversion():
    # With sigma=0 the process decays exponentially toward mu.
    state = ou_noise_init(action_dim=1, x0=1.0)
    key = jax.random.PRNGKey(0)
    for _ in range(500):
        _, state = ou_noise_sample(state, key, theta=0.15, mu=0.0, sigma=0.0, dt=0.1)
    assert abs(float(state.x[0])) < 1e-3


def test_ou_stationary_std():
    # OU stationary std = sigma / sqrt(2 theta) (in dt->continuous limit).
    state = ou_noise_init(action_dim=512)
    key = jax.random.PRNGKey(1)
    vals = []
    for i in range(2000):
        key, sub = jax.random.split(key)
        x, state = ou_noise_sample(state, sub, theta=0.15, sigma=0.2, dt=1e-2)
        if i > 500:
            vals.append(np.asarray(x))
    std = np.std(np.concatenate(vals))
    expected = 0.2 / np.sqrt(2 * 0.15)
    assert abs(std - expected) / expected < 0.15


def test_ou_reset_restores_x_and_decays_eps():
    state = ou_noise_init(action_dim=3, epsilon=1.0, x0=0.5)
    key = jax.random.PRNGKey(2)
    _, state = ou_noise_sample(state, key)
    state = ou_noise_reset(state, decay=0.1, x0=0.5)
    np.testing.assert_allclose(np.asarray(state.x), 0.5)
    assert abs(float(state.epsilon) - 0.9) < 1e-6


def test_noise_fns_are_jittable():
    sample = jax.jit(
        lambda s, k: gaussian_noise_sample(s, k, (4,)), static_argnums=()
    )
    out = sample(gaussian_noise_init(), jax.random.PRNGKey(0))
    assert out.shape == (4,)
    ou = jax.jit(ou_noise_sample)
    x, st = ou(ou_noise_init(2), jax.random.PRNGKey(0))
    assert x.shape == (2,)
