"""Tier-1 smokes for the megastep-vs-host data-plane microbench.

Two halves, mirroring the other benchmark smokes:

- the GENERATOR runs end-to-end at tiny shapes (so a refactor that breaks
  ``bench_megastep``/``run_microbench`` fails here, not at artifact-regen
  time) — timing ratios are NOT asserted at this scale (CPU noise);
- the COMMITTED artifact (``benchmarks/megastep_microbench.json``) keeps
  its schema and the acceptance headline: megastep >= host-path steps/s
  on the committed run, and strictly lower per-grad-step transfer bytes
  (zero for the device placement — the whole point of the data plane).
"""

from __future__ import annotations

import json
import os

import pytest

pytest.importorskip("jax")

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "megastep_microbench.json",
)


def test_generator_runs_at_small_shape(tmp_path):
    from benchmarks.megastep_microbench import run_microbench

    out_path = str(tmp_path / "megastep_microbench.json")
    out = run_microbench(
        out_path, batch=16, k=4, hidden=32, rows=1024, steps=3, repeats=1
    )
    assert os.path.exists(out_path)
    for name in ("host_block_k32", "hybrid_k32", "device_k32",
                 "device_per_k32"):
        row = out[name]
        assert row["steps_per_sec"] > 0
        assert row["transfer_bytes_per_grad_step"] >= 0
    # the structural (not timing) halves of the claim hold at ANY shape:
    assert out["device_k32"]["transfer_bytes_per_grad_step"] == 0.0
    # ISSUE 14: PER on, still zero per-grad-step traffic — the device
    # tree keeps the whole descent/write-back loop on-chip
    assert out["device_per_k32"]["transfer_bytes_per_grad_step"] == 0.0
    assert out["device_per_k32"]["per"] is True
    assert (
        out["hybrid_k32"]["transfer_bytes_per_grad_step"]
        < out["host_block_k32"]["transfer_bytes_per_grad_step"]
    )
    # hybrid per-grad-step H2D is exactly the [K, B] int32 idx + f32
    # weights upload amortized over K: B·(4+4) bytes per grad step
    assert out["hybrid_k32"]["h2d_bytes_per_grad_step"] == 16 * 8
    with open(out_path) as f:
        json.load(f)  # artifact is valid JSON


def test_committed_artifact_schema_and_headline():
    with open(ARTIFACT) as f:
        doc = json.load(f)
    assert doc["metric"] == "megastep_microbench"
    assert "backend" in doc and "on_chip_recipe" in doc
    for name in ("host_block_k32", "hybrid_k32", "device_k32",
                 "device_per_k32"):
        row = doc[name]
        assert row["steps_per_sec"] > 0
        assert "transfer_bytes_per_grad_step" in row
        assert "steps_per_sec_repeats" in row
    # the acceptance headline: megastep >= host steps/s on the committed
    # run, strictly lower transfer bytes (0 on the device placement)
    assert doc["device_k32_steps_ratio"] >= 1.0
    assert doc["hybrid_k32_steps_ratio"] >= 1.0
    assert doc["device_k32"]["transfer_bytes_per_grad_step"] == 0.0
    assert doc["device_per_k32"]["transfer_bytes_per_grad_step"] == 0.0
    assert (
        doc["hybrid_k32"]["transfer_bytes_per_grad_step"]
        < doc["host_block_k32"]["transfer_bytes_per_grad_step"]
    )


def test_committed_mfu_sweep_has_megastep_rows():
    sweep = os.path.join(os.path.dirname(ARTIFACT), "mfu_sweep_results.json")
    with open(sweep) as f:
        rows = json.load(f)
    mega = [r for r in rows if str(r.get("config", "")).startswith("megastep")]
    assert mega, "mfu_sweep_results.json lost its megastep rows"
    for r in mega:
        assert r["bench"] == "mfu_sweep"
        assert "backend" in r  # CPU placeholders must be distinguishable
        assert r["transfer_bytes_per_grad_step"] == 0.0
        assert r["steps_per_sec"] > 0
