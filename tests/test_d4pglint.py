"""d4pglint self-tests: per check, a bad fixture that MUST fire, a good
fixture that must NOT, and proof the ``# d4pglint: disable=`` suppression
silences exactly that finding. Plus: the repo itself lints clean (the
tier-1 contract scripts/lint.sh enforces), and the benchmark/metrics
schema checker's own good/bad fixtures.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

import pytest

from tools.d4pglint import ALL_CHECKS, lint_paths, lint_source
from tools.d4pglint.schema_check import (
    check_benchmark_json,
    check_metrics_jsonl,
)

# A minimal conforming model of serve/protocol.py: every wire id, the
# protocol-module codecs, MAX_PAYLOAD-bounded framing, and the prober
# endpoint. Shared with tests/test_wholeprog.py (its multi-file endpoint
# fixtures need a clean protocol module in the map) so the two files can
# never drift on what "conforming" means.
PROTOCOL_GOOD_SRC = """
import struct

MAX_PAYLOAD = 1 << 20
PROTOCOL_VERSION = 1
HEADER = struct.Struct("<2sBBII")

ACT = 1
ACT_OK = 2
OVERLOADED = 3
ERROR = 4
HEALTHZ = 5
HEALTHZ_OK = 6
HELLO = 7
HELLO_OK = 8
WINDOWS = 9
WINDOWS_OK = 10
ACT2 = 11
WINDOWS2 = 12
FEEDBACK = 13
FEEDBACK_OK = 14


class ProtocolError(Exception):
    pass


def read_frame(stream):
    length = 0
    if length > MAX_PAYLOAD:
        raise ProtocolError("oversized")
    return HEALTHZ_OK, 0, b""


def write_frame(sock, msg_type, req_id, payload=b""):
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError("oversized")


def encode_act(obs, deadline_us=0):
    return b""


def decode_act(payload, obs_dim):
    return payload, 0


def encode_act2(obs, deadline_us=0, policy_id="default", qos=0, tenant=""):
    return b""


def decode_act2(payload):
    return payload, 0, "default", 0, ""


def encode_action(action):
    return b""


def decode_action(payload):
    return payload


def encode_feedback(reward, action, next_obs, log_prob=0.0,
                    terminated=False, truncated=False, policy_id=None):
    return b""


def decode_feedback(payload):
    return {}


def probe_healthz(host, port):
    msg_type, _req_id, payload = read_frame(None)
    if msg_type != HEALTHZ_OK:
        raise ProtocolError("unexpected healthz reply")
    return {}
"""

# (check_id, relpath, bad_src, good_src) — relpath matters: several checks
# key on the manifests in tools/d4pglint/config.py.
FIXTURES = [
    (
        "host-jax-import",
        "d4pg_tpu/runtime/actor_pool.py",
        """
        import numpy as np
        import jax
        """,
        """
        import numpy as np

        def act():
            import jax  # lazy: only the paths that need it pay it
            return jax
        """,
    ),
    (
        "lock-blocking-call",
        "d4pg_tpu/runtime/x.py",
        """
        import time

        def flush(self):
            with self._lock:
                time.sleep(0.1)
        """,
        """
        import time

        def flush(self):
            with self._lock:
                n = self._n
            time.sleep(0.1)

        def wait_pattern(self):
            with self._cond:
                self._cond.wait(1.0)  # cv pattern: waiting the held lock

        def join_strings(self):
            with self._lock:
                return ", ".join(self.parts)  # str.join is not a thread join
        """,
    ),
    (
        "shared-mutable-state",
        "d4pg_tpu/runtime/x.py",
        """
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._loop, name="p", daemon=True).start()

            def _loop(self):
                self.count = 1
        """,
        """
        import threading

        class Pump:
            _THREAD_SAFE = ("count",)  # single-writer, readers tolerate staleness

            def start(self):
                threading.Thread(target=self._loop, name="p", daemon=True).start()

            def _loop(self):
                self.count = 1
                with self._lock:
                    self.guarded = 2
        """,
    ),
    (
        "wall-clock-deadline",
        "d4pg_tpu/runtime/x.py",
        """
        import time

        def deadline():
            return time.time() + 5.0
        """,
        """
        import time

        def deadline():
            return time.monotonic() + 5.0
        """,
    ),
    (
        "broad-except",
        "d4pg_tpu/runtime/x.py",
        """
        def f():
            try:
                g()
            except Exception:
                pass
        """,
        """
        def f():
            try:
                g()
            except ValueError:
                pass
            try:
                g()
            except Exception as e:
                print(f"context: {e}")
            try:
                g()
            except BaseException:
                raise
        """,
    ),
    (
        "jit-purity",
        "d4pg_tpu/agent/x.py",
        """
        import jax
        import numpy as np

        def step(x):
            return np.asarray(x) + 1

        jit_step = jax.jit(step)
        """,
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        def step(x):
            return jnp.asarray(x) + 1

        jit_step = jax.jit(step)

        def host_helper(x):
            return np.asarray(x)  # not jit-traced: fine
        """,
    ),
    (
        "hot-path-alloc",
        "d4pg_tpu/replay/per.py",
        """
        import numpy as np

        class PrioritizedReplayBuffer:
            def sample_block(self, b, k):
                return np.stack([self.rows[i] for i in range(k)])
        """,
        """
        import numpy as np

        class PrioritizedReplayBuffer:
            def sample_block(self, b, k):
                def mk():  # nested lazy init closure: exempt
                    return np.zeros((b, k))

                st = self._staging or mk()
                st[:] = 0
                return st
        """,
    ),
    (
        "thread-discipline",
        "d4pg_tpu/runtime/x.py",
        """
        import threading

        def start(fn):
            threading.Thread(target=fn).start()
        """,
        """
        import threading

        def start(fn):
            threading.Thread(target=fn, name="worker", daemon=True).start()
        """,
    ),
    (
        "global-rng",
        "d4pg_tpu/replay/x.py",
        """
        import numpy as np

        def draw(n):
            return np.random.uniform(size=n)
        """,
        """
        import numpy as np

        def draw(n, rng=None):
            rng = rng or np.random.default_rng(0)
            return rng.uniform(size=n)
        """,
    ),
    (
        "unbounded-retry",
        "d4pg_tpu/runtime/x.py",
        """
        import time

        def connect_forever(mk):
            while True:
                try:
                    return mk()
                except OSError:
                    time.sleep(1.0)

        def connect_forever_while1(mk):
            while 1:
                try:
                    return mk()
                except OSError:
                    time.sleep(1.0)
        """,
        """
        import time

        from d4pg_tpu.utils.retry import Backoff

        def connect_bounded(mk):
            for attempt in Backoff(max_attempts=5):
                try:
                    return mk()
                except OSError:
                    continue  # Backoff sleeps between bounded attempts
            raise TimeoutError("gave up")

        def connect_range(mk):
            for attempt in range(5):
                try:
                    return mk()
                except OSError:
                    time.sleep(0.1)  # bounded by the range
            raise TimeoutError("gave up")

        def loop_that_escapes(mk):
            while True:
                try:
                    return mk()
                except OSError:
                    raise  # no silent retry: escapes the loop

        def condition_bounded(mk, stop):
            while not stop.is_set():
                try:
                    return mk()
                except OSError:
                    time.sleep(0.1)  # terminates via the loop condition

        def event_loop_with_inner_bounded_retry(q, send):
            while True:  # long-lived event loop, not itself a retry
                msg = q.get()
                for attempt in range(3):
                    try:
                        send(msg)
                        break
                    except OSError:
                        time.sleep(0.1)  # bounded by the INNER range
        """,
    ),
    (
        "device-loop-transfer",
        "d4pg_tpu/runtime/megastep.py",
        """
        import numpy as np

        def megastep_uniform_body(config, k, batch, state, ring, key):
            idx = np.arange(4)
            return ring.size.item()
        """,
        """
        import jax.numpy as jnp
        import numpy as np

        def megastep_uniform_body(config, k, batch, state, ring, key):
            idx = jnp.arange(4)

            def loss(p):  # nested closures trace too — but this is clean
                return jnp.sum(p[idx])

            return loss

        def host_helper(x):
            return np.asarray(x).item()  # not in the manifest: fine
        """,
    ),
    (
        "counter-discipline",
        "d4pg_tpu/serve/stats.py",
        """
        import threading

        class ServeStats:
            def __init__(self):
                self._lock = threading.Lock()
                self.requests_total = 0

            def admit(self):
                self.requests_total += 1
        """,
        """
        import threading

        class ServeStats:
            def __init__(self):
                self._lock = threading.Lock()
                self.requests_total = 0

            def admit(self):
                with self._lock:
                    self.requests_total += 1
        """,
    ),
    (
        "loop-blocking-call",
        "d4pg_tpu/serve/router.py",
        """
        import time

        class Router:
            def _serve_conn(self, conn, msg_type, req_id, payload):
                time.sleep(0.1)
                conn.sock.recv(4096)
        """,
        """
        class Router:
            def _serve_conn(self, conn, msg_type, req_id, payload):
                # conn.send is the frame-queue API (append + wake): exempt
                conn.send(2, req_id, payload)
                # a stall becomes a loop TIMER, never a sleep on the loop
                self._loop.call_later(
                    0.1, self._admit_and_route, conn, req_id
                )

            def _admit_and_route(self, conn, req_id):
                def done(f):
                    # nested def, not in the manifest: runs on the
                    # replica link's reader thread, so result() is fine
                    conn.send(2, req_id, f.result())

                self._dispatch().add_done_callback(done)
        """,
    ),
    (
        "lock-order",
        "d4pg_tpu/runtime/x.py",
        """
        import threading

        class Pump:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()

            def forward(self):
                with self._alock:
                    with self._block:
                        pass

            def backward(self):
                with self._block:
                    with self._alock:
                        pass
        """,
        """
        import threading

        class Pump:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()

            def forward(self):
                with self._alock:
                    with self._block:  # consistent global order
                        pass

            def backward(self):
                with self._alock:
                    pass
                with self._block:  # sequential, never nested inverted
                    pass
        """,
    ),
    (
        "protocol-conformance",
        "d4pg_tpu/serve/protocol.py",
        """
        ACT = 1
        ACT_OK = 1
        """,
        PROTOCOL_GOOD_SRC,
    ),
    (
        "thread-lifecycle",
        "d4pg_tpu/runtime/x.py",
        """
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(
                    target=self._loop, name="pump", daemon=True
                )
                self._t.start()

            def _loop(self):
                self._cond.wait()

            def close(self):
                pass
        """,
        """
        import threading

        class Pump:
            _DETACHED_THREADS = ("pump-conn",)  # unblocked by close()'s socket close

            def start(self):
                self._t = threading.Thread(
                    target=self._loop, name="pump", daemon=True
                )
                self._t.start()
                threading.Thread(
                    target=self._loop, name="pump-conn", daemon=True
                ).start()

            def _loop(self):
                with self._cond:
                    self._cond.wait(0.5)

            def close(self):
                self._t.join(timeout=5)
        """,
    ),
    (
        "flowcheck",
        "d4pg_tpu/fleet/actor.py",
        # A consumed-but-unbooked exit: the else arm pops the pending
        # entry, then raises without booking a terminal disposition —
        # the exact FleetLink bug class the pass exists to catch. The
        # good twin books "dropped" before raising.
        """
        import threading

        class FleetLink:
            def __init__(self, on_ack):
                self._pending = {}
                self._pending_lock = threading.Lock()
                self._on_ack = on_ack

            def _read_loop(self):
                while True:
                    msg_type, req_id = self._recv()
                    with self._pending_lock:
                        n = self._pending.pop(req_id, None)
                    if n is None:
                        continue
                    if msg_type == 1:
                        self._on_ack("accepted", n)
                    elif msg_type == 2:
                        self._on_ack("stale", n)
                    elif msg_type == 3:
                        self._on_ack("shed", n)
                    else:
                        raise RuntimeError("unexpected reply type")

            def _fail_send(self, req_id):
                with self._pending_lock:
                    n = self._pending.pop(req_id, None)
                if n is not None:
                    self._on_ack("dropped", n)

        class FleetActor:
            def __init__(self):
                self._stats_lock = threading.Lock()
                self._stats = {}

            def _inc(self, key, n=1):
                with self._stats_lock:
                    self._stats[key] += n

            def run(self):
                self._inc("windows_emitted", 1)

            def _on_ack(self, kind, n):
                self._inc(
                    {
                        "accepted": "windows_acked",
                        "stale": "windows_stale",
                        "shed": "windows_shed",
                        "dropped": "windows_dropped_reconnect",
                    }[kind],
                    n,
                )
        """,
        """
        import threading

        class FleetLink:
            def __init__(self, on_ack):
                self._pending = {}
                self._pending_lock = threading.Lock()
                self._on_ack = on_ack

            def _read_loop(self):
                while True:
                    msg_type, req_id = self._recv()
                    with self._pending_lock:
                        n = self._pending.pop(req_id, None)
                    if n is None:
                        continue
                    if msg_type == 1:
                        self._on_ack("accepted", n)
                    elif msg_type == 2:
                        self._on_ack("stale", n)
                    elif msg_type == 3:
                        self._on_ack("shed", n)
                    else:
                        self._on_ack("dropped", n)
                        raise RuntimeError("unexpected reply type")

            def _fail_send(self, req_id):
                with self._pending_lock:
                    n = self._pending.pop(req_id, None)
                if n is not None:
                    self._on_ack("dropped", n)

        class FleetActor:
            def __init__(self):
                self._stats_lock = threading.Lock()
                self._stats = {}

            def _inc(self, key, n=1):
                with self._stats_lock:
                    self._stats[key] += n

            def run(self):
                self._inc("windows_emitted", 1)

            def _on_ack(self, kind, n):
                self._inc(
                    {
                        "accepted": "windows_acked",
                        "stale": "windows_stale",
                        "shed": "windows_shed",
                        "dropped": "windows_dropped_reconnect",
                    }[kind],
                    n,
                )
        """,
    ),
    (
        "unused-suppression",
        "d4pg_tpu/runtime/x.py",
        """
        import time

        def f():
            return time.monotonic()  # d4pglint: disable=wall-clock-deadline  -- stale: the fix landed
        """,
        """
        import time

        def g():
            return time.time()  # d4pglint: disable=wall-clock-deadline  -- human-facing timestamp
        """,
    ),
]

assert {f[0] for f in FIXTURES} == set(ALL_CHECKS), "fixture per check"


def _lint(src: str, relpath: str, check: str):
    return lint_source(textwrap.dedent(src), relpath, checks=[check])


@pytest.mark.parametrize(
    "check,relpath,bad,good", FIXTURES, ids=[f[0] for f in FIXTURES]
)
def test_bad_fixture_fires_good_fixture_clean(check, relpath, bad, good):
    findings, _ = _lint(bad, relpath, check)
    assert findings, f"{check}: bad fixture produced no finding"
    assert all(f.check == check for f in findings)
    findings, _ = _lint(good, relpath, check)
    assert findings == [], f"{check}: good fixture fired: {findings}"


@pytest.mark.parametrize(
    "check,relpath,bad,good", FIXTURES, ids=[f[0] for f in FIXTURES]
)
def test_suppression_silences_exactly_the_finding(check, relpath, bad, good):
    findings, _ = _lint(bad, relpath, check)
    lines = textwrap.dedent(bad).splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # d4pglint: disable={check}  -- test fixture"
    suppressed_src = "\n".join(lines)
    findings2, suppressed = lint_source(
        suppressed_src, relpath, checks=[check]
    )
    assert findings2 == []
    assert len(suppressed) == len(findings)  # audited, not vanished
    # an unrelated id must NOT suppress it
    other = next(c for c in ALL_CHECKS if c != check)
    lines = textwrap.dedent(bad).splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # d4pglint: disable={other}"
    findings3, _ = lint_source("\n".join(lines), relpath, checks=[check])
    assert len(findings3) == len(findings)


def test_repo_lints_clean():
    """The tier-1 contract: zero findings over the product-code manifest
    (suppressions are allowed — they carry justifications)."""
    findings, _suppressed = lint_paths()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\ndef f():\n    return time.time()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.d4pglint", str(bad)],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 1
    assert "wall-clock-deadline" in proc.stdout
    ok = tmp_path / "ok.py"
    ok.write_text("import time\n\ndef f():\n    return time.monotonic()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.d4pglint", str(ok)],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stdout


def test_lint_counts_at_least_eight_checks():
    assert len(ALL_CHECKS) >= 8  # ISSUE-4 acceptance floor


# ------------------------------------------------------------- schema checks
def test_benchmark_schema_good_and_bad(tmp_path):
    good_obj = tmp_path / "a.json"
    good_obj.write_text(json.dumps({"backend": "cpu", "x": 1.0}))
    assert check_benchmark_json(str(good_obj)) == []
    good_list = tmp_path / "b.json"
    good_list.write_text(json.dumps([{"bench": "mfu_sweep", "x": 1}]))
    assert check_benchmark_json(str(good_list)) == []
    for bad_doc in ["{", json.dumps({"x": 1}), json.dumps([{"x": 1}]),
                    json.dumps(3), json.dumps({})]:
        p = tmp_path / "bad.json"
        p.write_text(bad_doc)
        assert check_benchmark_json(str(p)), f"accepted: {bad_doc!r}"


def test_metrics_jsonl_schema_good_and_bad(tmp_path):
    good = tmp_path / "metrics.jsonl"
    good.write_text(
        json.dumps({"step": 1, "t": 0.5, "loss": 1.25}) + "\n"
        + json.dumps({"step": 2, "t": 1.0, "loss": 1.0}) + "\n"
    )
    assert check_metrics_jsonl(str(good)) == []
    for bad_row in [
        "not json",
        json.dumps({"t": 0.5}),                       # no step
        json.dumps({"step": "three", "t": 0.5}),      # non-int step
        json.dumps({"step": 1}),                      # no t
        json.dumps({"step": 1, "t": 0.1, "env": "pendulum"}),  # non-numeric
    ]:
        p = tmp_path / "bad.jsonl"
        p.write_text(bad_row + "\n")
        assert check_metrics_jsonl(str(p)), f"accepted: {bad_row!r}"


def test_schema_check_passes_on_committed_artifacts():
    from tools.d4pglint.schema_check import check_tree

    assert check_tree("/root/repo") == []
