"""League controller (ISSUE 15): PBT lifecycle, crash consistency, the
variant capability in the fleet HELLO, and the manifest-verified fork.

Fast by design: the controller is JAX-free and the learners here are
``scripts/league_stub_learner.py`` — a deterministic stand-in that
speaks exactly train.py's league surface (manifest-attested checkpoints,
exit-75 drain, trainer_meta attestation, genome-determined fitness) in
milliseconds. The REAL-learner league runs in ``scripts/league_smoke.sh``
(tier-1) and chaos_soak leg 9.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "scripts", "league_stub_learner.py")

from d4pg_tpu.league.controller import (  # noqa: E402
    LeagueConfig,
    LeagueController,
    genome_argv,
    perturb_genome,
)
from d4pg_tpu.runtime import manifest as ckpt_manifest  # noqa: E402
from d4pg_tpu.utils import procs  # noqa: E402


# ----------------------------------------------------------------- helpers
def _stub_config(tmp_path, genomes, **kw):
    base = dict(
        league_dir=str(tmp_path / "league"),
        learner_argv=[sys.executable, STUB, "--checkpoint-interval", "4",
                      "--eval-interval", "2", "--tick-seconds", "0.03"],
        genomes=genomes,
        seed=7,
        generations=1,
        poll_interval_s=0.1,
        gen_timeout_s=60.0,
        drain_timeout_s=20.0,
        attest_timeout_s=20.0,
        observe_timeout_s=20.0,
    )
    base.update(kw)
    return LeagueConfig(**base)


GOOD = {"lr_actor": 1e-4, "max_episode_steps": 50}
MID = {"lr_actor": 1e-4, "max_episode_steps": 200}
BAD = {"lr_actor": 1e-3, "max_episode_steps": 250}


def _league_pids(league_dir):
    """Every live process whose cmdline names the league dir — the
    zero-orphans scan."""
    out = []
    for name in os.listdir("/proc"):
        if name.isdigit():
            cmd = procs.pid_cmdline(int(name))
            if str(league_dir) in cmd and "league_stub" in cmd:
                out.append(int(name))
    return out


# --------------------------------------------------------------- jax-free
def test_league_controller_is_jax_free():
    """The supervision contract: a controller restart after kill -9 must
    cost milliseconds, so importing the whole league package (plus the
    manifest/procs machinery it forks and kills through) must never load
    the JAX runtime — manifest-enforced (HOST_ONLY_MODULES) and proven
    here in a clean subprocess."""
    code = (
        "import sys\n"
        "import d4pg_tpu.league.controller, d4pg_tpu.league.__main__\n"
        "import d4pg_tpu.runtime.manifest, d4pg_tpu.utils.procs\n"
        "bad = [m for m in sys.modules if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, bad\n"
        "print('JAXFREE_OK')\n"
    )
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
    )
    assert p.returncode == 0 and "JAXFREE_OK" in p.stdout, (
        p.stdout + p.stderr
    )


# ------------------------------------------------------------------ genome
def test_perturb_genome_seeded_and_bounded():
    g = {"lr_actor": 1e-4, "tau": 0.001, "max_episode_steps": 200}
    a = perturb_genome(g, random.Random(3))
    b = perturb_genome(g, random.Random(3))
    assert a == b  # seeded: the league's decision stream replays
    assert a["max_episode_steps"] == 200  # structural genes untouched
    for k in ("lr_actor", "tau"):
        assert a[k] in (g[k] * 0.8, g[k] * 1.25)


def test_genome_argv_refuses_unknown_keys():
    with pytest.raises(ValueError, match="unknown genome key"):
        genome_argv({"learning_rate": 1e-4})
    argv = genome_argv({"lr_actor": 1e-4, "batch_size": 16})
    assert "--lr-actor" in argv and "--bsize" in argv


# ------------------------------------------------- league metrics columns
def test_metrics_logger_static_league_columns(tmp_path):
    """MetricsLogger(static=...) stamps the league identity columns onto
    EVERY row, numeric (the schema_check contract: integer-valued pair,
    both or neither)."""
    from d4pg_tpu.runtime.metrics import MetricsLogger
    from tools.d4pglint.schema_check import check_metrics_jsonl

    log = MetricsLogger(
        str(tmp_path), use_tensorboard=False,
        static={"variant_id": 3, "league_generation": 1},
    )
    log.log(1, {"critic_loss": 0.5})
    log.log(2, {"critic_loss": 0.4, "eval_return_mean": -100.0})
    log.close()
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    rows = [json.loads(l) for l in open(path)]
    assert all(
        r["variant_id"] == 3.0 and r["league_generation"] == 1.0
        for r in rows
    )
    assert check_metrics_jsonl(path) == []
    # the pair rule: a row carrying one column without the other is a
    # schema violation (hand-rolled writers can't half-adopt the contract)
    with open(path, "a") as f:
        f.write(json.dumps({"step": 3, "t": 1.0, "variant_id": 3.0}) + "\n")
    errs = check_metrics_jsonl(path)
    assert errs and "pair" in errs[0]


# ------------------------------------------------- fleet HELLO variant cap
def test_negotiate_fleet_variant_exact_match():
    from d4pg_tpu.replay.source import LEGACY_ACTOR_CAPS, negotiate_fleet

    learner = {"obs_mode": "f32", "her": False, "obs_norm": False,
               "variant": 4}
    # pre-variant actor (and pre-ISSUE-13 legacy) declare variant 0
    chosen, gaps = negotiate_fleet(learner, LEGACY_ACTOR_CAPS)
    assert chosen is None
    assert [g.code for g in gaps] == ["variant_mismatch"]
    chosen, gaps = negotiate_fleet(
        learner,
        {"obs_modes": ["f32"], "her": False, "obs_norm": False,
         "variant": 4},
    )
    assert gaps == () and chosen["variant"] == 4
    # default learner x default actor: byte-compat cell stays open
    learner["variant"] = 0
    chosen, gaps = negotiate_fleet(learner, LEGACY_ACTOR_CAPS)
    assert gaps == () and chosen["variant"] == 0


def test_ingest_refuses_wrong_variant_with_structured_reason():
    import socket

    import numpy as np  # noqa: F401  (buffer stub needs nothing)

    from d4pg_tpu.fleet import wire
    from d4pg_tpu.fleet.ingest import IngestServer
    from d4pg_tpu.serve import protocol

    class _Buf:
        def add_batch(self, t):
            pass

    srv = IngestServer(
        _Buf(), obs_dim=3, action_dim=1, n_step=3, gamma=0.99,
        caps={"obs_mode": "f32", "her": False, "obs_norm": False,
              "variant": 9},
    ).start()
    try:
        def hello(caps):
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            protocol.write_frame(
                s, protocol.HELLO, 1,
                wire.encode_hello(
                    actor_id="a", env="e", obs_dim=3, action_dim=1,
                    n_step=3, gamma=0.99, generation=0, caps=caps,
                ),
            )
            frame = protocol.read_frame(s.makefile("rb"))
            return s, frame

        # assigned elsewhere: refused with the machine-readable code
        s, (t, _r, payload) = hello(
            {"obs_modes": ["f32"], "her": False, "obs_norm": False,
             "variant": 2}
        )
        assert t == protocol.ERROR
        doc = wire.decode_refusal(payload)
        assert [g["code"] for g in doc["gaps"]] == ["variant_mismatch"]
        s.close()
        # correctly assigned: accepted, variant echoed for the actor's
        # wrong-port check
        s, (t, _r, payload) = hello(
            {"obs_modes": ["f32"], "her": False, "obs_norm": False,
             "variant": 9}
        )
        assert t == protocol.HELLO_OK
        assert wire.decode_hello_ok(payload)["caps"]["variant"] == 9
        s.close()
    finally:
        srv.close()


# --------------------------------------------------------- checkpoint fork
def _fake_run(run_dir, steps, content=b"x" * 512):
    ckpt = os.path.join(run_dir, "checkpoints")
    os.makedirs(ckpt, exist_ok=True)
    meta = os.path.join(ckpt, "trainer_meta.json")
    for step in steps:
        sd = os.path.join(ckpt, str(step))
        os.makedirs(sd, exist_ok=True)
        with open(os.path.join(sd, "params.bin"), "wb") as f:
            f.write(content + str(step).encode())
        with open(meta, "w") as f:
            json.dump({"env_steps": step}, f)
        ckpt_manifest.write_manifest_file(
            ckpt_manifest.manifest_path(ckpt, step),
            ckpt_manifest.build_manifest(step, sd, [meta]),
        )
    return ckpt


def test_fork_copies_newest_intact_steps_and_side_files(tmp_path):
    src = _fake_run(str(tmp_path / "src"), [4, 8, 12])
    dst = str(tmp_path / "dst" / "checkpoints")
    copied = ckpt_manifest.fork_checkpoint(src, dst, depth=2)
    assert copied == [8, 12]
    assert ckpt_manifest.intact_steps(dst) == [8, 12]
    assert os.path.exists(os.path.join(dst, "trainer_meta.json"))
    # fork refuses to clobber an existing run's checkpoints
    with pytest.raises(FileExistsError):
        ckpt_manifest.fork_checkpoint(src, dst, depth=2)


def test_fork_skips_torn_source_step(tmp_path):
    """A truncated source step is skipped at fork exactly as restore
    would skip it — the clone only ever receives attested bytes."""
    src = _fake_run(str(tmp_path / "src"), [4, 8, 12])
    victim = os.path.join(src, "12", "params.bin")
    with open(victim, "rb+") as f:
        f.truncate(100)
    dst = str(tmp_path / "dst" / "checkpoints")
    assert ckpt_manifest.fork_checkpoint(src, dst, depth=2) == [4, 8]


def test_fork_retries_when_live_source_gc_wins_the_race(tmp_path, monkeypatch):
    """The source learner is ALIVE while it is forked, so Orbax GC can
    delete a just-verified step mid-copy — the fork must re-verify and
    retry (bounded), never crash the controller (review finding)."""
    src = _fake_run(str(tmp_path / "src"), [4, 8, 12])
    dst = str(tmp_path / "dst" / "checkpoints")
    real = ckpt_manifest._copy_fork
    calls = []

    def racy(src_dir, dst_dir, good):
        if not calls:
            calls.append(1)
            raise FileNotFoundError("step 12 directory is gone (GC)")
        return real(src_dir, dst_dir, good)

    monkeypatch.setattr(ckpt_manifest, "_copy_fork", racy)
    assert ckpt_manifest.fork_checkpoint(src, dst, depth=2) == [8, 12]
    assert ckpt_manifest.intact_steps(dst) == [8, 12]


def test_clone_corrupt_falls_back_to_older_forked_step(tmp_path):
    """The clone_corrupt chaos shape: the newest FORKED step torn after
    the copy — verify-on-restore (stub learner == restore_verified
    semantics) must fall back to the older copied step."""
    src = _fake_run(str(tmp_path / "src"), [4, 8])
    dst = str(tmp_path / "dst" / "checkpoints")
    assert ckpt_manifest.fork_checkpoint(src, dst, depth=2) == [4, 8]
    from d4pg_tpu.chaos import truncate_checkpoint_step

    truncate_checkpoint_step(os.path.join(dst, "8"))
    assert ckpt_manifest.intact_steps(dst) == [4]


# --------------------------------------------------------- controller runs
def test_league_promotes_planted_better_variant(tmp_path):
    """The acceptance shape, in-process: 3 variants with fitness
    separation baked into the genomes — the worst is culled, the clone
    forks from the planted winner, attests, and promotes."""
    ctl = LeagueController(_stub_config(tmp_path, [GOOD, MID, BAD]))
    rc = ctl.run()
    assert rc == 0
    s = ctl.state
    assert s["generation"] == 1 and s["promotions"] == 1
    assert s["rollbacks"] == 0
    [edge] = s["lineage"]
    assert edge["parent"] == 1 and edge["reason"] == "clone"  # GOOD is uid 1
    # the worst (BAD, uid 3) was culled and its slot re-seeded
    assert ctl._variant(3)["status"] == "retired"
    assert ctl._variant(3)["killed"] == 1
    assert ctl._members()[2] == edge["child"]
    # clone's genome is a perturbation of the winner's
    child = ctl._variant(edge["child"])
    assert child["genome"]["lr_actor"] in (1e-4 * 0.8, 1e-4 * 1.25)
    # summary passes its own schema gate + zero orphans
    from tools.d4pglint.schema_check import check_league_soak

    assert check_league_soak(
        os.path.join(ctl.dir, "league_summary.json")
    ) == []
    assert _league_pids(ctl.dir) == []


def test_rollback_on_fitness_below_bar_reforks_unperturbed(tmp_path):
    """The canary-rollback shape: genomes planted so ANY perturbation of
    the winner scores below the culled victim's bar — the clone must
    roll back and the slot re-seed with the parent's exact recipe."""
    g1 = {"lr_actor": 1e-4, "max_episode_steps": 50}
    g2 = {"lr_actor": 1e-4, "max_episode_steps": 51}
    g3 = {"lr_actor": 1e-4, "max_episode_steps": 52}
    ctl = LeagueController(_stub_config(tmp_path, [g1, g2, g3]))
    rc = ctl.run()
    assert rc == 0
    s = ctl.state
    assert s["rollbacks"] == 1 and s["promotions"] == 1
    reasons = [e["reason"] for e in s["lineage"]]
    assert reasons == ["clone", "rollback_refork"]
    refork = s["lineage"][-1]
    # the re-fork carries the parent's UNPERTURBED genome
    assert ctl._variant(refork["child"])["genome"] == g1
    from tools.d4pglint.schema_check import check_league_soak

    assert check_league_soak(
        os.path.join(ctl.dir, "league_summary.json")
    ) == []


def test_crash_looping_variant_quarantined(tmp_path):
    """The actor-pool discipline at league scale: a variant whose genome
    'diverges' (stub crash-loop) burns its seeded Backoff budget and is
    quarantined; the league completes on the survivors."""
    diverged = {"lr_actor": 1.0, "max_episode_steps": 50}
    ctl = LeagueController(_stub_config(
        tmp_path, [GOOD, MID, diverged], restart_max_attempts=2,
    ))
    rc = ctl.run()
    assert rc == 0
    v3 = ctl._variant(3)
    assert v3["status"] == "quarantined"
    assert v3["restarts"] == 2  # the full bounded budget, then no more
    assert v3["exited_err"] == 3  # initial + 2 restarts, all crashed
    assert ctl.state["generation"] == 1  # survivors carried the league
    from tools.d4pglint.schema_check import check_league_soak

    assert check_league_soak(
        os.path.join(ctl.dir, "league_summary.json")
    ) == []


def test_all_terminal_league_stops_loudly(tmp_path):
    """Every member quarantined ⇒ the league must STOP with rc 1 (the
    all-quarantined actor-pool rule), never spin silently forever."""
    diverged = {"lr_actor": 1.0, "max_episode_steps": 50}
    ctl = LeagueController(_stub_config(
        tmp_path, [diverged, dict(diverged), dict(diverged)],
        restart_max_attempts=1,
    ))
    rc = ctl.run()
    assert rc == 1
    # the stop fires as soon as fewer than two members can ever rank
    # again — at least two are quarantined by then, none keeps running
    statuses = [
        ctl._variant(u)["status"] for u in ctl._members().values()
    ]
    assert statuses.count("quarantined") >= 2
    assert _league_pids(ctl.dir) == []


def test_lone_survivor_league_stops_loudly(tmp_path):
    """One live member left (the rest quarantined) ⇒ exploit/explore can
    never rank again — the league must stop loudly, not poll forever
    (review finding: the all-terminal check alone missed this)."""
    diverged = {"lr_actor": 1.0, "max_episode_steps": 50}
    ctl = LeagueController(_stub_config(
        tmp_path, [GOOD, dict(diverged), dict(diverged)],
        restart_max_attempts=1,
    ))
    rc = ctl.run()
    assert rc == 1
    statuses = sorted(
        ctl._variant(u)["status"] for u in ctl._members().values()
    )
    assert statuses.count("quarantined") == 2
    assert _league_pids(ctl.dir) == []


def test_crash_looping_refork_gives_up_slot_bounded(tmp_path):
    """A rollback re-fork that itself crash-loops must GIVE THE SLOT UP
    (one bounded outcome), never re-fork forever (review finding: the
    quarantine branch used to re-enter _rollback for reforks too)."""
    ctl = LeagueController(_stub_config(tmp_path, [GOOD, MID, BAD]))
    pending = {"gen": 0, "actions": []}
    action = {
        "phase": "observing", "kill_uid": 3, "src_uid": 1,
        "child_uid": 4, "genome": dict(GOOD),
        "reason": "rollback_refork", "bar_fitness": None,
        "fork_steps": [],
    }
    pending["actions"].append(action)
    ctl.state["variants"]["4"] = ctl._new_variant(
        4, 2, dict(GOOD), parent=1, born_gen=0
    )
    ctl.state["variants"]["4"]["status"] = "quarantined"
    ctl.state["pending"] = pending
    before = ctl.state["next_uid"]
    ctl._observe(pending, action)
    assert action["phase"] == "done"                  # resolved, not re-forked
    assert ctl.state["next_uid"] == before            # no new clone minted
    assert ctl.state["rollbacks"] == 1
    ctl.shutdown()


def test_journal_refuses_mismatched_resume_args(tmp_path):
    ctl = LeagueController(_stub_config(tmp_path, [GOOD, MID, BAD]))
    ctl.shutdown()
    with pytest.raises(RuntimeError, match="journal disagrees"):
        LeagueController(_stub_config(tmp_path, [GOOD, MID, BAD], seed=8))
    with pytest.raises(RuntimeError, match="journal disagrees"):
        LeagueController(_stub_config(tmp_path, [GOOD, MID]))


# ------------------------------------------- controller crash consistency
def _controller_argv(league_dir, *, chaos=None, generations=1):
    argv = [
        sys.executable, "-m", "d4pg_tpu.league",
        "--dir", str(league_dir), "--seed", "7",
        "--generations", str(generations),
        "--poll-interval", "0.1", "--gen-timeout", "60",
        "--drain-timeout", "20", "--attest-timeout", "20",
        "--observe-timeout", "20",
        "--genome", "lr_actor=1e-4,max_episode_steps=50",
        "--genome", "lr_actor=1e-4,max_episode_steps=200",
        "--genome", "lr_actor=1e-3,max_episode_steps=250",
    ]
    if chaos:
        argv += ["--chaos", chaos]
    argv += ["--", sys.executable, STUB, "--checkpoint-interval", "4",
             "--eval-interval", "2", "--tick-seconds", "0.03"]
    return argv


def test_controller_kill9_resumes_same_generation(tmp_path):
    """THE crash-consistency contract (ISSUE 15 satellite): kill -9 the
    controller at a seeded-random instant mid-generation; the restarted
    controller must resume the SAME generation (never double-book),
    re-adopt or restart the learners, finish the league, and leave zero
    orphaned learner processes with the lineage DAG intact."""
    league = tmp_path / "league"
    proc = subprocess.Popen(
        _controller_argv(league), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # wait until a generation is IN FLIGHT (journal holds pending work)
    journal = league / "league.json"
    deadline = time.monotonic() + 60
    pending_seen = False
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            pytest.fail(
                "controller finished before the kill window: "
                + proc.stdout.read()[-2000:]
            )
        try:
            doc = json.loads(journal.read_text())
            if doc.get("pending"):
                pending_seen = True
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    assert pending_seen, "no pending generation within the deadline"
    gen_before = doc["generation"]
    # the seeded-random instant: anywhere inside the generation's apply
    time.sleep(random.Random(71).uniform(0.0, 0.4))
    proc.kill()  # SIGKILL: no cleanup, no journal flush
    proc.wait()
    # learners were spawned as their own sessions: some may still be
    # alive (that is the point — the restart must re-adopt them)
    rerun = subprocess.run(
        _controller_argv(league), cwd=REPO, capture_output=True,
        text=True, timeout=120,
    )
    assert rerun.returncode == 0, rerun.stdout[-3000:]
    assert "journal_resumed" in rerun.stdout
    final = json.loads(journal.read_text())
    # the SAME generation resumed and committed exactly once
    assert final["generation"] == gen_before + 1
    assert final["pending"] is None
    assert final["promotions"] + final["rollbacks"] >= 1
    # lineage DAG intact + accounting identity exact (schema-gated)
    from tools.d4pglint.schema_check import check_league_soak

    assert check_league_soak(str(league / "league_summary.json")) == []
    # zero orphaned learner processes
    assert _league_pids(league) == []


def test_controller_kill_chaos_site_roundtrip(tmp_path):
    """The chaos-site version of the same story: controller_kill@N
    SIGKILLs the controller from the inside; variant_kill@N SIGKILLs a
    learner group (restarted under Backoff); clone_corrupt@N tears the
    fork (the clone falls back to the older copied step)."""
    league = tmp_path / "league"
    first = subprocess.run(
        _controller_argv(
            league, chaos="seed=5;variant_kill@2;clone_corrupt@1;"
                          "controller_kill@8",
        ),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert first.returncode == -signal.SIGKILL, first.stdout[-2000:]
    assert "controller_kill: SIGKILL self" in first.stdout
    assert "variant_kill: SIGKILL" in first.stdout
    rerun = subprocess.run(
        _controller_argv(league), cwd=REPO, capture_output=True,
        text=True, timeout=120,
    )
    assert rerun.returncode == 0, rerun.stdout[-3000:]
    final = json.loads((league / "league.json").read_text())
    assert final["generation"] == 1 and final["pending"] is None
    assert _league_pids(league) == []


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
