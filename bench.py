"""Benchmark: learner grad-steps/sec on TPU vs a reference-style CPU-torch learner.

Prints ONE JSON line:
  {"metric": "learner_grad_steps_per_sec", "value": N, "unit": "steps/s",
   "vs_baseline": R}

The measured workload is the flagship D4PG configuration from BASELINE.json
(HalfCheetah-scale: obs 17, act 6, 3×256 MLPs, C51 with 51 atoms, batch 256):
one full fused train step — two target forwards, categorical projection,
critic CE + actor −E[Q] losses, both Adam updates, Polyak — steady-state
with donated device buffers.

``vs_baseline`` divides by the same step implemented the way the reference
runs it (pure CPU PyTorch + a NumPy host-side projection, mirroring the
structure of ``ddpg.py:200-255`` without copying it). The reference publishes
no numbers (BASELINE.md), so its measured-here CPU throughput is the
comparison point.

PINNED PROTOCOL (the ratio is only comparable under these conditions):
- The TPU side includes device-side batch sampling (RBG randint + random
  gather from a 65k-row pool) exactly as the on-device trainer samples its
  ring — NOT pre-materialized batches. The gather is the dominant cost at
  this model size: compute-only (pre-gathered [K, B] batches) measures
  ~10x higher (see benchmarks/projection_bench.py), so a number without
  the gather is NOT this metric.
- The torch baseline runs single-threaded on the host core
  (``torch.set_num_threads(1)``); its absolute steps/s is printed in the
  JSON line (``baseline_steps_per_sec``) so ratio drift is attributable —
  on this 1-core host, any concurrent load deflates the baseline and
  inflates the ratio. Run the bench on an otherwise idle host.
- The baseline is builder-authored (reference-STYLE): the true reference
  loop cannot run standalone — its replay writes are gated on HER
  (SURVEY.md quirk #14) so the buffer stays empty and ``train()`` crashes.
  Always carry this caveat next to the headline ratio.

The line also carries the round-6 roofline-attack comparisons, all under
the same pinned protocol: fused Pallas projection+loss vs the XLA oracle
(steps/s + XLA-accounted bytes per grad step, both dtypes) and the host
replay→device pipeline with the double-buffered prefetch off/on — plus,
round 7, the per-stage host data-plane breakdown (sample / h2d_stage /
train_dispatch / priority_writeback, ms per dispatch) for the legacy
sampler vs the native batched ``sample_block`` path (docs/data_plane.md).

Round 11 adds the megastep data plane to the line:
``transfer_bytes_per_grad_step_{host,hybrid,megastep}`` (counted from the
exact arrays staged/fetched per dispatch at the flagship K=32 shape) next
to ``megastep_steps_per_sec`` — the device-resident-replay loop
(``bench_megastep``) whose per-grad-step transfer count is zero by
construction and enforced by the ``--debug-guards`` transfer budget.

When the default backend fails to initialize (wedged tunnel), the output
is ONE parseable ``{"error": "tpu_unreachable"}`` JSON line, never a raw
traceback; ``--allow-cpu-fallback`` appends a second, clearly-marked
CPU-backend host-pipeline line. The chip-independent regression guards are
``benchmarks/fused_microbench.py`` (committed
``benchmarks/cpu_microbench.json``),
``benchmarks/host_pipeline_microbench.py`` (committed
``benchmarks/host_pipeline_microbench.json``), and
``benchmarks/megastep_microbench.py`` (committed
``benchmarks/megastep_microbench.json``).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _probe_default_backend() -> str | None:
    """Default-backend platform name, probed in a subprocess; None on failure.

    A wedged TPU tunnel has been observed to raise (BENCH_r05: backend
    setup error), hang ``jax.devices()`` outright (MULTICHIP_r05 rc=124),
    or fail fast so jax silently falls back to the CPU backend (round 6 —
    which would grind the full TPU protocol on one CPU core until the
    driver's timeout). The shared subprocess probe
    (``d4pg_tpu.utils.backend_probe``) shields this process from the first
    two; the caller detects the third from the returned platform name.
    Either way the driver gets ONE parseable
    ``{"error": "tpu_unreachable"}`` line, never a traceback/timeout kill.
    """
    from d4pg_tpu.utils.backend_probe import probe_default_backend

    platform, _ = probe_default_backend()
    return platform


BATCH = 256
OBS_DIM = 17
ACT_DIM = 6
HIDDEN = 256
ATOMS = 51
V_MIN, V_MAX = -150.0, 150.0
WARMUP_DISPATCHES = 3
MEASURE_DISPATCHES = 16
BASELINE_MEASURE_STEPS = 50


# Dense bf16/f32 peak matmul throughput per chip, by device_kind, for the
# MFU denominator (public figures; conservative bf16 numbers). Unknown kinds
# report mfu=null rather than a made-up denominator.
PEAK_TFLOPS = {
    "TPU v2": 45.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}

# HBM peak bandwidth per chip (GB/s, public figures) — the roofline
# denominator that makes "the gather, not the MXU, is the bottleneck"
# falsifiable (VERDICT round-3 missing #3).
PEAK_HBM_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def match_peak(table: dict, device_kind: str):
    """Longest-prefix-first startswith match: 'TPU v5' must not shadow
    'TPU v5p'/'TPU v5 lite' just because of dict insertion order
    (ADVICE round-3)."""
    for key in sorted(table, key=len, reverse=True):
        if device_kind.startswith(key):
            return table[key]
    return None


def model_flops_per_step(config, state, ex_batch):
    """Model FLOPs (and XLA byte-traffic estimate) per grad step — the ONE
    place this number is derived, so every generator's MFU line shares the
    same oracle instead of re-deriving (and drifting on) it.

    FLOPs come from XLA's own cost model on the UNFUSED single-step
    program (VERDICT round-2 missing #3). The fused K-step program can't
    be used for this: XLA's cost analysis counts a while-loop body once,
    not ×K trip count (verified: the K=512 scan reports ~1/512th of the
    real count), so the single step — whose program XLA counts exactly;
    spot-checked against a hand-counted matmul — is the honest unit.

    The second return is XLA's post-fusion HLO memory-traffic estimate
    (operand + output bytes per fused op): params + both Adam moment sets
    + grads + activations + the batch rows the pool gather touches. Same
    single-step caveat as flops (scan bodies count once).

    Returns ``(flops_per_step, bytes_per_step)``, either side ``None``
    when the probe is unavailable — benchmark timings land without it.
    """
    try:
        from d4pg_tpu.agent import jit_train_step

        single = jit_train_step(config)
        cost = single.lower(state, ex_batch).compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
        bytes_accessed = float(cost.get("bytes accessed", 0.0)) or None
        return flops, bytes_accessed
    except Exception:  # d4pglint: disable=broad-except  -- optional XLA
        # cost-analysis probe; benchmark timings land without it
        return None, None


def mfu_fields(
    steps_per_sec,
    flops_per_step,
    bytes_per_step=None,
    *,
    device_kind=None,
):
    """Achieved-vs-roofline fields for one benchmark row: grad-steps/s ×
    the :func:`model_flops_per_step` oracle vs this chip's peaks.

    Compute side: ``achieved_tflops`` / ``mfu`` against ``PEAK_TFLOPS``.
    Single-digit MFU is EXPECTED at the flagship shape and stated as such:
    3×256 MLPs at batch 256 are far below MXU-saturating sizes and the
    random pool gather dominates (see benchmarks/projection_bench.py for
    the compute-only ceiling and benchmarks/mfu_sweep.py for where the
    same framework's MFU lands with MXU-saturating shapes).

    Memory side (when ``bytes_per_step`` is given): the flagship
    workload's arithmetic intensity is flops/bytes ≈ 17 FLOP/B (measured:
    715.7 MFLOP / 42.9 MB per step) — far below the ~240 FLOP/B ridge of
    a v5e (197 TF/s ÷ 819 GB/s), so HBM utilization, not MFU, is the axis
    this workload can saturate. ``xla_bytes_util`` is named for what it
    IS: a ratio of XLA cost-analysis "bytes accessed" (which
    double-counts fused operand/output traffic) to physical peak — it can
    legitimately exceed 1.0 and means "at the HBM wall by XLA byte
    accounting", not measured DRAM traffic (ADVICE round-4: the old name
    hbm_util read as a physical utilization).

    Unknown chips report no mfu/xla_bytes_util rather than a made-up
    denominator; a ``None`` flops oracle yields an empty dict.
    """
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    out = {}
    if flops_per_step:
        achieved = flops_per_step * steps_per_sec
        out["flops_per_grad_step"] = flops_per_step
        out["achieved_tflops"] = achieved / 1e12
        peak = match_peak(PEAK_TFLOPS, device_kind)
        if peak is not None:
            out["peak_tflops"] = peak
            out["mfu"] = achieved / (peak * 1e12)
    if bytes_per_step:
        out["bytes_per_grad_step"] = bytes_per_step
        out["achieved_gbps"] = bytes_per_step * steps_per_sec / 1e9
        peak_bw = match_peak(PEAK_HBM_GBPS, device_kind)
        if peak_bw is not None:
            out["peak_gbps"] = peak_bw
            out["xla_bytes_util"] = out["achieved_gbps"] / peak_bw
    return out


def bench_tpu(
    compute_dtype: str = "float32",
    *,
    batch: int = BATCH,
    hidden: int = HIDDEN,
    pixel: bool = False,
    k_steps: int = 512,
    warmup: int = WARMUP_DISPATCHES,
    measure: int = MEASURE_DISPATCHES,
    pool_rows: int = 65_536,
    projection_backend: str = "xla",
) -> dict:
    """Learner throughput the TPU-native way: K train steps fused into one
    XLA program via ``lax.scan`` (as the on-device trainer runs them,
    ``d4pg_tpu/runtime/on_device.py``), so dispatch overhead — which the
    per-step Python loop of the reference pays on every single step — is
    amortized away. Batches are resampled on device per step from a
    device-resident pool to keep the memory traffic honest.

    Timing protocol: dispatches are pipelined (enqueued without per-call
    syncs, exactly as the training loop runs) and the clock stops on a
    forced device→host transfer of the final dispatch's loss — which
    transitively depends on every step in the chain (the train state is
    donated and serially threaded), so nothing can finish after the timer.

    The keyword knobs exist for ``benchmarks/mfu_sweep.py``, which sweeps
    batch/width/pixel configs through this SAME pinned protocol (a second
    copy of the protocol would drift); the flagship line uses the defaults.
    """
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.models.critic import DistConfig

    if pixel:
        obs_dim, act_dim, pixel_shape = 48 * 48 * 2, 1, (48, 48, 2)
    else:
        obs_dim, act_dim, pixel_shape = OBS_DIM, ACT_DIM, None
    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        pixel_shape=pixel_shape,
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
        compute_dtype=compute_dtype,
        projection_backend=projection_backend,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    POOL = pool_rows
    pool = {
        "obs": jnp.asarray(rng.normal(size=(POOL, obs_dim)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, size=(POOL, act_dim)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, size=POOL), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(POOL, obs_dim)), jnp.float32),
        "discount": jnp.full((POOL,), 0.99, jnp.float32),
        "weights": jnp.ones((POOL,), jnp.float32),
    }
    pool = jax.device_put(pool)
    # K grad steps per dispatch: ≥512 amortizes per-call latency into the
    # ~40 µs/step compute asymptote (measured: K=64→~6k, K=256→~21k,
    # K≥512→~23-24k steps/s on one v5e core through a tunneled link).
    K = k_steps
    import functools

    from d4pg_tpu.agent.d4pg import fused_train_scan, gather_batches

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_k(state, pool, key):
        # Same fused gather+scan program the on-device trainer runs
        # (d4pg_tpu/runtime/on_device.py step 4). The pool is an ARGUMENT,
        # not a closure capture: captured arrays become jaxpr constants
        # inlined into the serialized HLO, and a pixel pool (~150 MB)
        # blows past the remote-compile endpoint's request limit.
        idx = jax.random.randint(key, (K, batch), 0, POOL)
        state, metrics, _ = fused_train_scan(config, state, gather_batches(pool, idx))
        return state, metrics["critic_loss"]

    # Achieved-vs-roofline numbers share one oracle (model_flops_per_step)
    # and one field builder (mfu_fields) across every generator, so
    # "gather/latency-bound at tiny-MLP sizes" is a measured number that
    # can't drift between bench_tpu, bench_megastep and the mfu_sweep rows.
    flops_per_step, bytes_per_step = model_flops_per_step(
        config, state, {k: v[:batch] for k, v in pool.items()}
    )
    device_kind = jax.devices()[0].device_kind

    key = jax.random.PRNGKey(1)
    for _ in range(warmup):
        key, k = jax.random.split(key)
        state, losses = run_k(state, pool, k)
    float(losses[-1])  # true sync: value transfer, not just block_until_ready
    iters = measure
    t0 = time.perf_counter()
    for _ in range(iters):
        key, k = jax.random.split(key)
        state, losses = run_k(state, pool, k)
    float(losses[-1])  # depends on the whole donated-state chain
    dt = time.perf_counter() - t0
    steps_per_sec = iters * K / dt
    out = {"steps_per_sec": steps_per_sec}
    out.update(
        mfu_fields(
            steps_per_sec,
            flops_per_step,
            bytes_per_step,
            device_kind=device_kind,
        )
    )
    return out


def bench_host_pipeline(
    prefetch: bool,
    *,
    steps: int = 300,
    batch: int = BATCH,
    compute_dtype: str = "bfloat16",
    rows: int = 65_536,
    tree_backend: str = "auto",
    sampler: str = "legacy",
    k: int = 1,
    hidden: int = HIDDEN,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
) -> dict:
    """HOST replay→device pipeline: grad-steps/s + per-stage breakdown.

    Measures exactly the loop the host trainer runs per dispatch — PER
    sample, H2D staging, jitted train step, priority write-back with the
    one-step lag — with ``prefetch=True`` adding the double buffer: batch
    N+1 is sampled and its H2D copy started while step N runs
    (``runtime/trainer.py``'s ``_sample_staged`` discipline, replicated
    here without env deps so the bench runs on any host).

    Every stage is timed with :class:`StageTimers` under the same names a
    training run writes to metrics.jsonl (sample / h2d_stage /
    train_dispatch / priority_writeback), so the result carries
    ``stage_ms_per_dispatch`` and ``host_ms_per_dispatch`` (sample + stage
    + write-back — the host share of the critical path) next to the
    steps/s headline.

    ``sampler`` selects the host data-plane generation under test:

    - ``"legacy"`` — the PR 1 path: per-batch ``sample()`` (or
      ``sample_many`` + per-field ``np.stack`` for fused k>1 dispatches),
      per-field fancy-index gathers;
    - ``"block"`` — the native batched path: ``sample_block`` delivers the
      [K, B] block from ONE backend call into preallocated staging (with
      ``tree_backend="native"``: descent + weights + gen capture + gather
      all in C, zero steady-state allocation).

    ``steps`` counts DISPATCHES; grad-steps/s = steps·k / wall.
    """
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.agent import D4PGConfig, create_train_state, jit_train_step
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.replay.per import PrioritizedReplayBuffer, SampledIndices
    from d4pg_tpu.replay.uniform import Transition
    from d4pg_tpu.utils.profiling import StageTimers

    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
        compute_dtype=compute_dtype,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    if k == 1:
        step_fn = jit_train_step(config)
    else:
        import functools

        from d4pg_tpu.agent.d4pg import fused_train_scan

        step_fn = jax.jit(
            functools.partial(fused_train_scan, config), donate_argnums=(0,)
        )
    rng = np.random.default_rng(0)
    buf = PrioritizedReplayBuffer(rows, obs_dim, act_dim, tree_backend=tree_backend)
    buf.add_batch(
        Transition(
            rng.normal(size=(rows, obs_dim)).astype(np.float32),
            rng.uniform(-1, 1, size=(rows, act_dim)).astype(np.float32),
            rng.uniform(-1, 0, size=rows).astype(np.float32),
            rng.normal(size=(rows, obs_dim)).astype(np.float32),
            np.full(rows, 0.99, np.float32),
        )
    )
    timers = StageTimers(annotate_prefix=None)
    # Per-dispatch link traffic, counted from the exact host arrays the
    # loop stages H2D (batch fields + IS weights) and fetches D2H
    # (priorities): the regression-checked transfer_bytes_per_grad_step
    # the megastep data plane exists to zero out.
    xfer = {"h2d": 0, "d2h": 0}

    def sample_staged(step):
        if sampler == "block":
            with timers.stage("sample"):
                blk = buf.sample_block(batch, k, rng, step=step)
                indices = blk.pop("indices")
                if k == 1:
                    indices = SampledIndices(indices.idx[0], indices.gen[0])
                    blk = {kk: v[0] for kk, v in blk.items()}
            with timers.stage("h2d_stage"):
                xfer["h2d"] += sum(v.nbytes for v in blk.values())
                dev = {kk: jnp.asarray(v) for kk, v in blk.items()}
        else:
            with timers.stage("sample"):
                if k == 1:
                    b = buf.sample(batch, rng, step=step)
                    indices = b.pop("indices")
                    host = b
                else:
                    samples = buf.sample_many(batch, k, rng, step=step)
                    indices = [s.pop("indices") for s in samples]
                    host = {
                        kk: np.stack([s[kk] for s in samples])
                        for kk in samples[0]
                    }
            with timers.stage("h2d_stage"):
                xfer["h2d"] += sum(v.nbytes for v in host.values())
                dev = {kk: jnp.asarray(v) for kk, v in host.items()}
        return indices, dev

    def write_back(pending):
        idx, pri_dev = pending
        with timers.stage("priority_writeback"):
            pri = np.asarray(pri_dev)
            xfer["d2h"] += pri.nbytes
            if isinstance(idx, list):
                for i, ix in enumerate(idx):
                    buf.update_priorities(ix, pri[i])
            else:
                buf.update_priorities(idx, pri)

    def run(n, i0, state, staged, pending):
        for i in range(i0, i0 + n):
            if staged is None:
                staged = sample_staged(i)
            indices, dev_batch = staged
            with timers.stage("train_dispatch"):
                state, _, priorities = step_fn(state, dev_batch)
            # prefetch: batch i+1 sampled + H2D started under step i's
            # (async-dispatched) device compute
            staged = sample_staged(i + 1) if prefetch else None
            if pending is not None:
                write_back(pending)
            if hasattr(priorities, "copy_to_host_async"):
                priorities.copy_to_host_async()
            pending = (indices, priorities)
        return state, staged, pending

    state, staged, pending = run(5, 0, state, staged=None, pending=None)
    jax.block_until_ready(state.step)
    timers.reset()
    xfer["h2d"] = xfer["d2h"] = 0
    t0 = time.perf_counter()
    state, staged, pending = run(steps, 5, state, staged, pending)
    jax.block_until_ready(state.step)
    dt = time.perf_counter() - t0
    stage_ms = timers.summary_ms(per=steps)
    host_ms = sum(
        stage_ms.get(s, 0.0) for s in ("sample", "h2d_stage", "priority_writeback")
    )
    return {
        "steps_per_sec": steps * k / dt,
        "dispatches_per_sec": steps / dt,
        "k": k,
        "sampler": sampler,
        "tree_backend": "native" if buf._use_native else "numpy",
        "prefetch": bool(prefetch),
        "stage_ms_per_dispatch": {kk: round(v, 4) for kk, v in stage_ms.items()},
        "host_ms_per_dispatch": round(host_ms, 4),
        # counted, not estimated: exactly the bytes this loop staged H2D
        # and fetched D2H during the measured window, per grad step
        "transfer_bytes_per_grad_step": round(
            (xfer["h2d"] + xfer["d2h"]) / (steps * k), 1
        ),
        "h2d_bytes_per_grad_step": round(xfer["h2d"] / (steps * k), 1),
        "d2h_bytes_per_grad_step": round(xfer["d2h"] / (steps * k), 1),
    }


def bench_megastep(
    *,
    placement: str = "device",
    per: bool = False,
    steps: int = 30,
    batch: int = BATCH,
    k: int = 32,
    hidden: int = HIDDEN,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
    rows: int = 65_536,
    compute_dtype: str = "float32",
    dp: int | None = None,
    device_tree_backend: str = "xla",
    projection_backend: str = "xla",
    fused_descent: bool = False,
    critic_ensemble: int = 0,
    ensemble_min_targets: int = 2,
) -> dict:
    """Device-resident replay + fused megastep: grad-steps/s and per-step
    transfer bytes (``runtime/megastep.py`` + ``replay/device_ring.py``).

    The apples-to-apples comparison point for :func:`bench_host_pipeline`
    at the same (batch, k, model) shape: the host pipeline pays a full
    batch upload + priority fetch per dispatch; the megastep pays ZERO
    per-grad-step transfers on the ``device`` (uniform, in-kernel draw)
    placement and only the [K, B] int32 index / f32 weight upload + [K, B]
    priority fetch on ``hybrid`` (PER). Transfer bytes are counted from
    the exact arrays staged/fetched, same accounting as the host bench.
    The one-time ring fill is reported separately (``ingest_bytes_total``)
    — it is experience ingest, not grad-step traffic.

    ``steps`` counts DISPATCHES; grad-steps/s = steps·k / wall.

    ``per=True`` (placement="device", ISSUE 14) runs DEVICE-RESIDENT PER:
    the priority segment tree lives in HBM (``replay/device_per.py``) and
    the descent, IS weights, and write-back all happen inside the fused
    megastep — prioritized replay at the same ZERO transfer bytes per
    grad step as the uniform row (``device_tree_backend`` selects the
    descent kernel: xla reference or the Pallas prefix-scan).

    ``fused_descent=True`` (ISSUE 16) runs the large-batch fused tier on
    top of device PER: descent + loss execute as ONE Pallas program per
    scan step (``make_megastep_device_per_fused``) — requires ``per``,
    single device, and ``projection_backend="pallas_fused"``.
    ``critic_ensemble``/``ensemble_min_targets`` stack REDQ members
    inside the same donated call for the ensemble-stacked megastep row.
    """
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.replay.device_ring import DeviceRingSync, device_ring_init
    from d4pg_tpu.replay.per import PrioritizedReplayBuffer
    from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
    from d4pg_tpu.runtime.megastep import (
        make_megastep_hybrid,
        make_megastep_uniform,
    )
    from d4pg_tpu.utils.profiling import StageTimers

    if placement not in ("device", "hybrid"):
        raise ValueError(f"placement must be device|hybrid, got {placement!r}")
    if dp and placement != "device":
        raise ValueError("dp>1 shards the uniform ring: placement must be device")
    if per and placement != "device":
        raise ValueError(
            "per=True is device-resident PER; hybrid IS the host-tree PER row"
        )
    if fused_descent and (not per or dp or projection_backend != "pallas_fused"):
        raise ValueError(
            "fused_descent=True is the single-device fused PER tier: needs "
            "per=True, dp=None, projection_backend='pallas_fused' (the same "
            "contract replay/source.py negotiates)"
        )
    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
        compute_dtype=compute_dtype,
        projection_backend=projection_backend,
        critic_ensemble=critic_ensemble,
        ensemble_min_targets=ensemble_min_targets if critic_ensemble else 2,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    mk = PrioritizedReplayBuffer if placement == "hybrid" else ReplayBuffer
    buf = mk(rows, obs_dim, act_dim)
    buf.add_batch(
        Transition(
            rng.normal(size=(rows, obs_dim)).astype(np.float32),
            rng.uniform(-1, 1, size=(rows, act_dim)).astype(np.float32),
            rng.uniform(-1, 0, size=rows).astype(np.float32),
            rng.normal(size=(rows, obs_dim)).astype(np.float32),
            np.full(rows, 0.99, np.float32),
        )
    )
    mesh = None
    if dp:
        from d4pg_tpu.parallel import make_mesh, shard_train_state

        mesh = make_mesh(dp=dp, tp=1)
        state = shard_train_state(state, mesh)
    if mesh is not None:
        from d4pg_tpu.replay.device_ring import ShardedDeviceRingSync

        ring = device_ring_init(rows, obs_dim, act_dim, mesh=mesh)
        sync = ShardedDeviceRingSync(buf, mesh)
    else:
        ring = device_ring_init(rows, obs_dim, act_dim)
        sync = DeviceRingSync(buf)
    dev_per = None
    if per:
        from d4pg_tpu.replay.device_per import DevicePerSync

        dev_per = DevicePerSync(rows, config.per_alpha, mesh=mesh)
        sync.tree_hook = dev_per.on_chunk  # seeds leaves with the fill below
    ring = sync.flush(ring)  # one-time fill: ingest, not grad-step traffic
    # Same single-step FLOPs oracle bench_tpu uses (model_flops_per_step:
    # a scanned body counts once, not ×K), so megastep MFU numbers line
    # up with the mfu_sweep rows instead of re-deriving the model cost.
    ex_batch = {
        "obs": jnp.zeros((batch, obs_dim), jnp.float32),
        "action": jnp.zeros((batch, act_dim), jnp.float32),
        "reward": jnp.zeros((batch,), jnp.float32),
        "next_obs": jnp.zeros((batch, obs_dim), jnp.float32),
        "discount": jnp.zeros((batch,), jnp.float32),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    flops_per_step, bytes_per_step = model_flops_per_step(
        config, state, ex_batch
    )
    timers = StageTimers(annotate_prefix=None)
    xfer = {"h2d": 0, "d2h": 0}
    if placement == "device":
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from d4pg_tpu.runtime.megastep import (
                make_megastep_device_per_sharded,
                make_megastep_uniform_sharded,
            )

            if per:
                mega = make_megastep_device_per_sharded(
                    config, k, batch, mesh,
                    tree_backend=device_tree_backend,
                )
            else:
                mega = make_megastep_uniform_sharded(config, k, batch, mesh)
            key = jax.device_put(
                jax.random.PRNGKey(1), NamedSharding(mesh, PartitionSpec())
            )
        else:
            if per and fused_descent:
                from d4pg_tpu.runtime.megastep import (
                    make_megastep_device_per_fused,
                )

                mega = make_megastep_device_per_fused(config, k, batch)
            elif per:
                from d4pg_tpu.runtime.megastep import (
                    make_megastep_device_per,
                )

                mega = make_megastep_device_per(
                    config, k, batch, tree_backend=device_tree_backend
                )
            else:
                mega = make_megastep_uniform(config, k, batch)
            key = jax.device_put(jax.random.PRNGKey(1))

        def one_dispatch(i, state, pending):
            nonlocal key
            with timers.stage("megastep_dispatch"):
                if dev_per is not None:
                    state, dev_per.tree, key, metrics = mega(
                        state, ring, dev_per.tree, key
                    )
                else:
                    state, key, metrics = mega(state, ring, key)
            return state, None
    else:
        mega = make_megastep_hybrid(config)

        def one_dispatch(i, state, pending):
            with timers.stage("sample"):
                idx, w, gen = buf.sample_block_indices(batch, k, rng, step=i)
            with timers.stage("h2d_stage"):
                idx32 = idx.astype(np.int32)
                xfer["h2d"] += idx32.nbytes + w.nbytes
                idx_dev = jax.device_put(idx32)
                w_dev = jax.device_put(w)
            with timers.stage("megastep_dispatch"):
                state, metrics, pri = mega(state, ring, idx_dev, w_dev)
            if pending is not None:  # one-dispatch-lag priority write-back
                p_idx, p_gen, p_pri = pending
                with timers.stage("priority_writeback"):
                    p = np.asarray(p_pri)
                    xfer["d2h"] += p.nbytes
                    from d4pg_tpu.replay.per import SampledIndices

                    buf.update_priorities(SampledIndices(p_idx, p_gen), p)
            if hasattr(pri, "copy_to_host_async"):
                pri.copy_to_host_async()
            return state, (idx, gen, pri)

    pending = None
    for i in range(3):  # warmup (compile + first dispatches)
        state, pending = one_dispatch(i, state, pending)
    jax.block_until_ready(state.step)
    timers.reset()
    xfer["h2d"] = xfer["d2h"] = 0
    t0 = time.perf_counter()
    for i in range(steps):
        state, pending = one_dispatch(3 + i, state, pending)
    jax.block_until_ready(state.step)
    dt = time.perf_counter() - t0
    stage_ms = timers.summary_ms(per=steps)
    host_ms = sum(
        stage_ms.get(s, 0.0)
        for s in ("sample", "h2d_stage", "priority_writeback")
    )
    out = {
        "steps_per_sec": steps * k / dt,
        "dispatches_per_sec": steps / dt,
        "k": k,
        "batch": batch,
        "placement": placement,
        "per": bool(per),
        "dp": int(dp or 1),
        "stage_ms_per_dispatch": {kk: round(v, 4) for kk, v in stage_ms.items()},
        "host_ms_per_dispatch": round(host_ms, 4),
        "transfer_bytes_per_grad_step": round(
            (xfer["h2d"] + xfer["d2h"]) / (steps * k), 1
        ),
        "h2d_bytes_per_grad_step": round(xfer["h2d"] / (steps * k), 1),
        "d2h_bytes_per_grad_step": round(xfer["d2h"] / (steps * k), 1),
        "ingest_bytes_total": sync.bytes_ingested,
        "ingest_chunks": sync.chunks_ingested,
    }
    out.update(
        mfu_fields(out["steps_per_sec"], flops_per_step, bytes_per_step)
    )
    return out


def bench_ensemble_capacity(
    *,
    ensemble: int = 4,
    mixtures: int = 5,
    hidden: int = 1024,
    batch: int = 512,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
    dp: int = 4,
    tp: int = 2,
    steps: int = 6,
) -> dict:
    """The capacity row the sharded learner unlocks (ROADMAP item 2): an
    E-wide REDQ critic ensemble with the mixture-of-Gaussians head at an
    MXU-friendly width, trained through the GSPMD dp×tp step with the
    member stack sharded over "tp" (the rule registry's stack_axes
    declaration — each device holds E/tp whole members).

    This is a SHARDING-load-bearing shape: E × hidden² params would
    replicate per device without the stack rules. Reports grad-steps/s on
    whatever backend is available (CPU here while the TPU tunnel is down;
    the artifact tags the backend and the on-chip recipe reruns as-is).
    """
    import jax

    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.parallel import (
        auto_parallel_train_step,
        make_mesh,
        shard_batch,
        shard_train_state,
        stack_axes_for,
    )

    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        critic_ensemble=ensemble,
        ensemble_min_targets=2,
        dist=DistConfig(kind="mixture_gaussian", num_mixtures=mixtures,
                        v_min=V_MIN, v_max=V_MAX),
    )
    mesh = make_mesh(dp=dp, tp=tp)
    ens_axis = "tp" if tp > 1 else None
    state = shard_train_state(
        create_train_state(config, jax.random.PRNGKey(0)), mesh,
        stack_axes=stack_axes_for(config, ens_axis),
    )
    step_fn = auto_parallel_train_step(
        config, mesh, donate=False, ensemble_axis=ens_axis
    )
    rng = np.random.default_rng(0)
    batch_np = {
        "obs": rng.normal(size=(batch, obs_dim)).astype(np.float32),
        "action": rng.uniform(-1, 1, (batch, act_dim)).astype(np.float32),
        "reward": rng.uniform(-1, 0, batch).astype(np.float32),
        "next_obs": rng.normal(size=(batch, obs_dim)).astype(np.float32),
        "discount": np.full(batch, 0.99, np.float32),
        "weights": np.ones(batch, np.float32),
    }
    dev_batch = shard_batch(batch_np, mesh)
    state, metrics, _ = step_fn(state, dev_batch)  # warmup compile
    jax.block_until_ready(metrics["critic_loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics, _ = step_fn(state, dev_batch)
    jax.block_until_ready(metrics["critic_loss"])
    dt = time.perf_counter() - t0
    member_params = sum(
        int(np.prod(x.shape[1:]))
        for x in jax.tree_util.tree_leaves(state.critic_params)
    )
    return {
        "config": "ensemble_mog_wide",
        "ensemble": ensemble,
        "ensemble_axis": ens_axis,
        "mixtures": mixtures,
        "hidden": hidden,
        "batch": batch,
        "dp": dp,
        "tp": tp,
        "steps_per_sec": steps / dt,
        "critic_params_per_member": member_params,
        "critic_loss": float(metrics["critic_loss"]),
    }


def bench_serve(
    *,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
    hidden: int = 64,
    max_batch: int = 32,
    max_wait_us: int = 1000,
    queue_limit: int | None = None,
    closed_profiles: tuple = ((1, 1), (4, 16)),
    open_load_factors: tuple = (0.5, 1.0, 2.0),
    open_rates: tuple | None = None,
    duration_s: float = 2.0,
    deadline_ms: float = 0.0,
    infer_delay_ms: float = 0.0,
    seed: int = 0,
) -> dict:
    """Open+closed-loop load generator against a live policy server.

    Starts a real :class:`~d4pg_tpu.serve.PolicyServer` (socket front-end,
    dynamic batcher, the whole stack) on loopback and drives it two ways:

    - **closed loop** — ``closed_profiles`` of ``(conns, window)``:
      pipelined connections each keeping ``window`` requests in flight,
      every completion immediately triggering the next send. ``(1, 1)``
      is the single-request throughput floor (each request pays the full
      batching window + device call — the honest cost of the serving
      configuration at one client); the widest profile saturates the
      batcher, and the headline ``batched_over_single`` ratio is
      saturated ÷ single throughput — the dynamic-batching win.
    - **open loop** — requests issued at a FIXED offered rate regardless
      of reply latency (pipelined client + pacer; catch-up bursts when the
      pacer falls behind), at multiples of the measured saturation
      throughput. This is the regime that exposes load shedding: past
      saturation a closed-loop client just slows down, an open-loop
      arrival process fills the queue and the server must say
      ``overloaded``. Reported per level: achieved rate, shed rate, and
      client-measured p50/p95/p99 of the requests that WERE served.

    Chip-independent by the same argument as ``bench_host_pipeline``: the
    batching/queue/socket mechanics are host CPU work; only the actor
    forward runs on the backend, and the comparison (batched vs single,
    shed behavior under offered load) holds on any device.
    """
    import threading

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.serve import Overloaded, PolicyBundle, PolicyClient, PolicyServer
    from d4pg_tpu.serve.bundle import actor_template
    from d4pg_tpu.serve.client import ConnectionClosed

    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
    )
    bundle = PolicyBundle(
        config=config,
        actor_params=actor_template(config),
        action_low=np.full(act_dim, -1.0, np.float32),
        action_high=np.full(act_dim, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "bench_serve"},
    )
    server = PolicyServer(
        bundle,
        port=0,
        max_batch=max_batch,
        max_wait_us=max_wait_us,
        queue_limit=queue_limit or 4 * max_batch,
        watch_bundle=False,
    )
    server.start()
    if infer_delay_ms:
        # Slow-device stub for the OVERLOAD scenario: on a few-core bench
        # host the stdlib load generator cannot out-pace the real batcher
        # (it serves >1k rps while the generator tops out about there), so
        # shedding never engages. Padding each device call makes the
        # capacity crossover — and the queue-full/deadline shed behavior
        # past it — measurable; the artifact labels these rows with the
        # stub delay so nobody reads them as device throughput.
        real_infer = server.batcher._infer

        def slow_infer(params, obs_batch):
            time.sleep(infer_delay_ms / 1e3)
            return real_infer(params, obs_batch)

        server.batcher._infer = slow_infer
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=obs_dim).astype(np.float32)

    def pct(lat):
        if not lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        v = np.percentile(np.asarray(lat), (50, 95, 99))
        return {f"p{q}_ms": round(float(x) * 1e3, 4) for q, x in zip((50, 95, 99), v)}

    def closed_loop(n_conns: int, window: int) -> dict:
        """``n_conns`` pipelined connections, each holding ``window``
        requests in flight (a completion immediately triggers the next
        send, from the client reader thread). conns×window is the closed
        population; (1, 1) is the strict one-at-a-time single-request
        floor. Pipelining — not a thread per simulated user — because N
        blocking threads measure the load generator's GIL thrash, not the
        server, on a few-core bench host."""
        lats: list[float] = []
        counts = {"done": 0, "shed": 0}
        lock = threading.Lock()
        stop = threading.Event()
        clients = [
            PolicyClient("127.0.0.1", server.port) for _ in range(n_conns)
        ]
        idle = threading.Semaphore(0)  # released once per drained chain

        def send_next(c):
            # No deadline in the closed phase: it measures CAPACITY, and a
            # deadline under a big closed population just converts queue
            # wait into shed/retry churn that reads as lost throughput.
            # Deadlines (the SLO) belong to the open-loop phase.
            t0 = time.perf_counter()
            fut = c.act_async(obs)

            def done(f, t0=t0):
                exc = f.exception()
                with lock:
                    if exc is None:
                        counts["done"] += 1
                        lats.append(time.perf_counter() - t0)
                    else:
                        counts["shed"] += 1  # closed loop: replaced below
                if stop.is_set() or isinstance(exc, ConnectionClosed):
                    idle.release()
                else:
                    send_next(c)  # back-to-back: the closed-loop property

            fut.add_done_callback(done)

        t_start = time.perf_counter()
        for c in clients:
            for _ in range(window):
                send_next(c)
        time.sleep(duration_s)
        stop.set()
        for _ in range(n_conns * window):
            idle.acquire(timeout=30)
        dt = time.perf_counter() - t_start
        for c in clients:
            c.close()
        return {
            "conns": n_conns,
            "window": window,
            "population": n_conns * window,
            "throughput_rps": round(counts["done"] / dt, 2),
            "completed": counts["done"],
            "shed": counts["shed"],
            **pct(lats),
        }

    def open_loop(offered_rps: float) -> dict:
        counts = {"ok": 0, "ok_window": 0, "shed": 0, "err": 0}
        lats: list[float] = []
        lock = threading.Lock()
        futures = []
        with PolicyClient("127.0.0.1", server.port) as c:
            interval = 1.0 / offered_rps
            t_next = time.perf_counter()
            t_end = t_next + duration_s
            while time.perf_counter() < t_end:
                now = time.perf_counter()
                if now < t_next:
                    time.sleep(t_next - now)
                t_next += interval
                t0 = time.perf_counter()
                fut = c.act_async(obs, deadline_ms=deadline_ms or None)

                def tally(f, t0=t0):
                    t_done = time.perf_counter()
                    exc = f.exception()
                    with lock:
                        if exc is None:
                            counts["ok"] += 1
                            # The rate only credits completions INSIDE the
                            # offered window — the tail that drains from
                            # the queue afterwards is latency, not
                            # sustained throughput (it would inflate
                            # achieved_rps by ~queue_limit/duration at
                            # overload levels).
                            if t_done <= t_end:
                                counts["ok_window"] += 1
                            lats.append(t_done - t0)
                        elif isinstance(exc, Overloaded):
                            counts["shed"] += 1
                        else:
                            counts["err"] += 1

                fut.add_done_callback(tally)
                futures.append(fut)
            deadline = time.perf_counter() + 30
            for fut in futures:
                try:
                    fut.result(max(0.01, deadline - time.perf_counter()))
                except Exception:  # d4pglint: disable=broad-except  -- shed/
                    # error outcomes were already tallied by the done
                    # callback; this wait only paces the collective drain
                    pass
        # Futures still unresolved after the collective wait never reached
        # a tally callback — count them as lost so total (and shed_rate's
        # denominator) reflects every request actually offered.
        lost = sum(1 for f in futures if not f.done())
        total = counts["ok"] + counts["shed"] + counts["err"] + lost
        return {
            "offered_rps": round(offered_rps, 2),
            "achieved_rps": round(counts["ok_window"] / duration_s, 2),
            "ok": counts["ok"],
            "shed": counts["shed"],
            "errors": counts["err"],
            "lost": lost,
            "shed_rate": round(counts["shed"] / total, 4) if total else None,
            **pct(lats),
        }

    try:
        closed = [closed_loop(m, w) for m, w in closed_profiles]
        single = closed[0]["throughput_rps"]
        saturated = max(c["throughput_rps"] for c in closed)
        levels = (
            list(open_rates)
            if open_rates
            else [max(1.0, f * saturated) for f in open_load_factors]
        )
        open_levels = [open_loop(r) for r in levels]
        health = server.healthz()
    finally:
        server.drain()
    return {
        "config": {
            "obs_dim": obs_dim,
            "act_dim": act_dim,
            "hidden": hidden,
            "max_batch": max_batch,
            "max_wait_us": max_wait_us,
            "queue_limit": queue_limit or 4 * max_batch,
            "duration_s": duration_s,
            "deadline_ms": deadline_ms,
            "infer_delay_ms": infer_delay_ms,
        },
        "closed_loop": closed,
        "single_rps": single,
        "saturated_rps": saturated,
        "batched_over_single": round(saturated / single, 3) if single else None,
        "open_loop": open_levels,
        "server": {
            k: health[k]
            for k in (
                "batches_total",
                "mean_batch",
                "batch_size_hist",
                "queue_depth_hist",
                "compile_count",
                "shed_total",
                "replies_ok",
                "params_version",
            )
            if k in health
        },
    }


def kill_policy_server_abruptly(server) -> None:
    """Simulate SIGKILL on an in-process :class:`PolicyServer`: abortive-
    close the listener and every live connection (peers see an RST —
    exactly a killed process's teardown as observed from the wire), no
    drain, nothing answered. Used by the router availability bench and the
    in-process router fault tests; the REAL ``kill -9`` path runs through
    subprocess replicas in scripts/router_smoke.sh and chaos_soak.sh."""
    server._shutdown.set()
    server._loop.stop_accepting()
    for c in server._loop.connections():
        c.abort()  # RST, queued replies dropped — wire-identical to kill -9
    server._loop.close(flush_timeout_s=0.5)
    server.batcher.stop(drain=False, timeout=5)


def bench_serve_router(
    *,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
    hidden: int = 64,
    max_batch: int = 16,
    max_wait_us: int = 2000,
    queue_limit: int | None = None,
    conns: int = 4,
    window: int = 16,
    duration_s: float = 2.0,
    kill_at_frac: float = 0.4,
    infer_delay_ms: float = 50.0,
    seed: int = 0,
) -> dict:
    """Closed-loop load through the replica front-end (``serve/router.py``).

    Two measurements, chip-independent by the bench_serve argument (the
    router adds pure host work on top of an already-host-dominated path):

    - **scaling** — the same closed population against a 1-replica fleet
      and a 2-replica fleet: aggregate throughput and p99. Replica
      capacity is pinned by a labeled ``infer_delay_ms`` slow-device stub
      (same device-bound-regime trick as the serve_microbench overload
      scenario): on a few-core bench host the real tiny-MLP batcher is
      HOST-bound, so a second in-process replica just contends for the
      same cores and the ratio measures GIL thrash, not dispatch. With
      per-replica capacity device-bound — the regime the committed
      serve_microbench shows a real device thread is in at saturation —
      the 1→2 replica ratio measures what the router actually adds.
    - **availability** — sustained closed-loop load on the 2-replica fleet
      while one replica is killed abruptly mid-stream. Reported: the
      accounting identity (submitted == ok + overloaded + failed — zero
      silent losses), availability (ok/submitted), router retries and
      ejections, and the latency percentiles THROUGH the failure.
    """
    import threading

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.serve import PolicyBundle, PolicyClient, PolicyServer, Router
    from d4pg_tpu.serve.bundle import actor_template
    from d4pg_tpu.serve.client import ConnectionClosed, Overloaded

    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
    )
    bundle = PolicyBundle(
        config=config,
        actor_params=actor_template(config),
        action_low=np.full(act_dim, -1.0, np.float32),
        action_high=np.full(act_dim, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "bench_serve_router"},
    )
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=obs_dim).astype(np.float32)

    def pct(lat):
        if not lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        v = np.percentile(np.asarray(lat), (50, 95, 99))
        return {f"p{q}_ms": round(float(x) * 1e3, 4) for q, x in zip((50, 95, 99), v)}

    def start_fleet(m: int):
        servers = [
            PolicyServer(
                bundle,
                port=0,
                max_batch=max_batch,
                max_wait_us=max_wait_us,
                queue_limit=queue_limit or 8 * max_batch,
                watch_bundle=False,
            )
            for _ in range(m)
        ]
        for s in servers:
            s.start()
            if infer_delay_ms:
                # Slow-device stub (see docstring): pins per-replica
                # capacity to the device thread so the 1-vs-2 comparison
                # measures dispatch, not host contention. sleep() releases
                # the GIL, unlike the real tiny-MLP CPU forward.
                real_infer = s.batcher._infer

                def slow_infer(params, obs_batch, _real=real_infer):
                    time.sleep(infer_delay_ms / 1e3)
                    return _real(params, obs_batch)

                s.batcher._infer = slow_infer
        router = Router(
            [("127.0.0.1", s.port) for s in servers],
            port=0,
            probe_interval_s=0.1,
            probe_timeout_s=1.0,
            readmit_after=1,
            retry_seed=seed,
        )
        router.start()
        router.wait_for_replicas(m, timeout_s=60)
        return servers, router

    def closed_loop(port: int, on_start=None) -> dict:
        """``conns`` pipelined connections × ``window`` in flight each;
        every completion (ok, shed, OR failed) immediately triggers the
        next send, so the outcome counts tally the full identity."""
        counts = {"submitted": 0, "ok": 0, "overloaded": 0, "error": 0}
        lats: list[float] = []
        lock = threading.Lock()
        stop = threading.Event()
        clients = [PolicyClient("127.0.0.1", port) for _ in range(conns)]
        idle = threading.Semaphore(0)  # released once per drained chain

        def send_next(c):
            t0 = time.perf_counter()
            with lock:
                counts["submitted"] += 1
            fut = c.act_async(obs)

            def done(f, t0=t0, c=c):
                exc = f.exception()
                with lock:
                    if exc is None:
                        counts["ok"] += 1
                        lats.append(time.perf_counter() - t0)
                    elif isinstance(exc, Overloaded):
                        counts["overloaded"] += 1
                    else:
                        counts["error"] += 1
                if stop.is_set() or isinstance(exc, ConnectionClosed):
                    idle.release()
                else:
                    send_next(c)

            fut.add_done_callback(done)

        t_start = time.perf_counter()
        for c in clients:
            for _ in range(window):
                send_next(c)
        if on_start is not None:
            on_start()
        time.sleep(duration_s)
        stop.set()
        for _ in range(conns * window):
            idle.acquire(timeout=30)
        dt = time.perf_counter() - t_start
        for c in clients:
            c.close()
        answered = counts["ok"] + counts["overloaded"] + counts["error"]
        return {
            "conns": conns,
            "window": window,
            "duration_s": round(dt, 3),
            "throughput_rps": round(counts["ok"] / dt, 2),
            **counts,
            "answered": answered,
            "lost": counts["submitted"] - answered,
            "identity_ok": answered == counts["submitted"],
            "availability": round(counts["ok"] / counts["submitted"], 6)
            if counts["submitted"]
            else None,
            **pct(lats),
        }

    out: dict = {
        "config": {
            "obs_dim": obs_dim,
            "act_dim": act_dim,
            "hidden": hidden,
            "max_batch": max_batch,
            "max_wait_us": max_wait_us,
            "conns": conns,
            "window": window,
            "duration_s": duration_s,
            "infer_delay_ms": infer_delay_ms,
            "queue_limit": queue_limit or 8 * max_batch,
        },
        "scaling": [],
    }
    # ---- scaling: 1 replica ------------------------------------------------
    servers, router = start_fleet(1)
    try:
        row = closed_loop(router.port)
        row["replicas"] = 1
        out["scaling"].append(row)
    finally:
        router.drain()
        for s in servers:
            s.drain()
    # ---- scaling: 2 replicas, then availability on the same fleet ----------
    servers, router = start_fleet(2)
    killed = []
    try:
        row = closed_loop(router.port)
        row["replicas"] = 2
        out["scaling"].append(row)

        def kill_one():
            def killer():
                time.sleep(kill_at_frac * duration_s)
                kill_policy_server_abruptly(servers[0])
                killed.append(servers[0])

            threading.Thread(
                target=killer, name="bench-replica-killer", daemon=True
            ).start()

        avail = closed_loop(router.port, on_start=kill_one)
        avail["replicas"] = 2
        avail["kill_at_s"] = round(kill_at_frac * duration_s, 3)
        health = router.healthz()
        avail["router_retries"] = health["retries"]
        avail["router_ejections"] = health["ejections"]
        out["availability"] = avail
    finally:
        router.drain()
        for s in servers:
            if s not in killed:
                s.drain()
    r1 = out["scaling"][0]["throughput_rps"]
    r2 = out["scaling"][1]["throughput_rps"]
    out["scaling_2_over_1"] = round(r2 / r1, 3) if r1 else None
    return out


def bench_serve_multitenant(
    *,
    obs_dim: int = OBS_DIM,
    act_dim: int = ACT_DIM,
    hidden: int = 64,
    max_batch: int = 16,
    max_wait_us: int = 2000,
    interactive_conns: int = 3,
    interactive_window: int = 4,
    bulk_conns: int = 3,
    bulk_window: int = 32,
    duration_s: float = 2.0,
    infer_delay_ms: float = 50.0,
    replica_capacity: int = 24,
    bulk_fraction: float = 0.4,
    slo_ms: float | None = None,
    scale_window_s: float = 1.0,
    seed: int = 0,
) -> dict:
    """The multi-tenant serving claims as numbers (ISSUE 12), chip-
    independent by the same slow-device-stub argument as
    ``bench_serve_router``:

    - **isolation** — the same interactive population (tenant ``web``,
      interactive class) measured alone and then with a FLOODING bulk
      tenant (``batch``, bulk class, a much deeper closed window)
      hammering the same 2-replica fleet through the router's class-aware
      admission (``replica_capacity``/``bulk_fraction``). The pinned
      claim: the flood cannot move interactive p99 past its SLO — bulk
      sheds first (``bulk_capacity``) and absorbs the overload — and the
      per-(tenant, class) accounting identity is exact on every row.

    - **autoscale_scaling** — one continuous interactive+bulk load while
      an :class:`~d4pg_tpu.serve.autoscaler.Autoscaler` (tight test
      cadence) grows the fleet 1 → 2 via an in-process replica pool
      through ``router.add_backend``: aggregate ok-rps measured in a
      window at 1 replica and again after admission of the 2nd must
      scale (the capacity claim; the subprocess-spawning pool is proven
      in chaos_soak.sh leg 7).

    ``slo_ms`` defaults to 8 × ``infer_delay_ms``: with device-bound
    replicas a protected interactive request rides ~1-2 batch times; an
    UNPROTECTED fleet under the bulk window would queue
    ~bulk_conns×bulk_window/max_batch batches deep (~10× that) — so the
    SLO separates the two regimes with margin on both sides."""
    import threading

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.serve import (
        Autoscaler,
        PolicyBundle,
        PolicyClient,
        PolicyServer,
        Router,
    )
    from d4pg_tpu.serve.autoscaler import ServingSignalSource
    from d4pg_tpu.serve.bundle import actor_template
    from d4pg_tpu.serve.client import ConnectionClosed, Overloaded

    slo_ms = slo_ms if slo_ms is not None else 8.0 * infer_delay_ms
    config = D4PGConfig(
        obs_dim=obs_dim,
        action_dim=act_dim,
        hidden_sizes=(hidden, hidden, hidden),
        dist=DistConfig(kind="categorical", num_atoms=ATOMS, v_min=V_MIN, v_max=V_MAX),
    )
    bundle = PolicyBundle(
        config=config,
        actor_params=actor_template(config),
        action_low=np.full(act_dim, -1.0, np.float32),
        action_high=np.full(act_dim, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "bench_serve_multitenant"},
    )
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=obs_dim).astype(np.float32)

    def make_server():
        s = PolicyServer(
            bundle,
            port=0,
            max_batch=max_batch,
            max_wait_us=max_wait_us,
            queue_limit=8 * max_batch,
            watch_bundle=False,
        )
        s.start()
        if infer_delay_ms:
            real_infer = s.batcher._infer

            def slow_infer(params, obs_batch, _real=real_infer):
                time.sleep(infer_delay_ms / 1e3)
                return _real(params, obs_batch)

            s.batcher._infer = slow_infer
        return s

    def pct(lat):
        if not lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        v = np.percentile(np.asarray(lat), (50, 95, 99))
        return {f"p{q}_ms": round(float(x) * 1e3, 4) for q, x in zip((50, 95, 99), v)}

    class Load:
        """Closed-loop population with a fixed (tenant, qos): every
        completion immediately re-sends, every outcome tallied — the
        client side of the accounting identity."""

        def __init__(self, port, conns, window, tenant, qos):
            self.counts = {"submitted": 0, "ok": 0, "overloaded": 0,
                           "error": 0}
            self.lats: list[float] = []
            self.lock = threading.Lock()
            self.stop = threading.Event()
            self.window = window
            self.clients = [
                PolicyClient("127.0.0.1", port, tenant=tenant, qos=qos)
                for _ in range(conns)
            ]
            self.idle = threading.Semaphore(0)

        def _send_next(self, c):
            t0 = time.perf_counter()
            with self.lock:
                self.counts["submitted"] += 1
            fut = c.act_async(obs)

            def done(f, t0=t0, c=c):
                exc = f.exception()
                with self.lock:
                    if exc is None:
                        self.counts["ok"] += 1
                        self.lats.append(time.perf_counter() - t0)
                    elif isinstance(exc, Overloaded):
                        self.counts["overloaded"] += 1
                    else:
                        self.counts["error"] += 1
                if self.stop.is_set() or isinstance(exc, ConnectionClosed):
                    self.idle.release()
                else:
                    self._send_next(c)

            fut.add_done_callback(done)

        def start(self):
            for c in self.clients:
                for _ in range(self.window):
                    self._send_next(c)
            return self

        def finish(self) -> dict:
            self.stop.set()
            for _ in range(len(self.clients) * self.window):
                self.idle.acquire(timeout=30)
            for c in self.clients:
                c.close()
            answered = (self.counts["ok"] + self.counts["overloaded"]
                        + self.counts["error"])
            return {
                **self.counts,
                "answered": answered,
                "identity_ok": answered == self.counts["submitted"],
                "shed_rate": round(
                    self.counts["overloaded"]
                    / max(self.counts["submitted"], 1), 6
                ),
                **pct(self.lats),
            }

    def start_fleet(m: int):
        servers = [make_server() for _ in range(m)]
        router = Router(
            [("127.0.0.1", s.port) for s in servers],
            port=0,
            probe_interval_s=0.1,
            probe_timeout_s=1.0,
            readmit_after=1,
            retry_seed=seed,
            replica_capacity=replica_capacity,
            bulk_fraction=bulk_fraction,
        )
        router.start()
        router.wait_for_replicas(m, timeout_s=60)
        return servers, router

    out: dict = {
        "config": {
            "obs_dim": obs_dim, "act_dim": act_dim, "hidden": hidden,
            "max_batch": max_batch, "max_wait_us": max_wait_us,
            "interactive_conns": interactive_conns,
            "interactive_window": interactive_window,
            "bulk_conns": bulk_conns, "bulk_window": bulk_window,
            "duration_s": duration_s, "infer_delay_ms": infer_delay_ms,
            "replica_capacity": replica_capacity,
            "bulk_fraction": bulk_fraction,
            "slo_ms": slo_ms,
        },
    }

    # ---- isolation: interactive alone, then under a bulk flood ------------
    servers, router = start_fleet(2)
    try:
        inter = Load(router.port, interactive_conns, interactive_window,
                     "web", "interactive").start()
        time.sleep(duration_s)
        baseline = inter.finish()
        inter = Load(router.port, interactive_conns, interactive_window,
                     "web", "interactive").start()
        flood = Load(router.port, bulk_conns, bulk_window,
                     "batch", "bulk").start()
        time.sleep(duration_s)
        inter_row = inter.finish()
        flood_row = flood.finish()
        h = router.healthz()
        tenants = h["tenants"]
        rows_ok = all(
            row["requests"] == row["answered"] for row in tenants.values()
        )
        out["isolation"] = {
            "interactive_baseline": baseline,
            "interactive_under_flood": inter_row,
            "bulk_flood": flood_row,
            "slo_ms": slo_ms,
            "interactive_p99_ms": inter_row["p99_ms"],
            "isolation_ok": (
                inter_row["identity_ok"]
                and flood_row["identity_ok"]
                and inter_row["p99_ms"] is not None
                and inter_row["p99_ms"] <= slo_ms
            ),
            "bulk_shed_rate": flood_row["shed_rate"],
            "shed_bulk_capacity": h["shed_bulk_capacity"],
            "shed_capacity": h["shed_capacity"],
            "tenants": tenants,
            "tenant_identity_ok": rows_ok,
            "router_identity_ok": (
                h["requests_total"] == h["answered_total"]
            ),
        }
    finally:
        router.drain()
        for s in servers:
            s.drain()

    # ---- autoscale_scaling: rps at 1 replica vs after the scale-up --------
    servers, router = start_fleet(1)
    spawned: list = []

    def scale_up():
        s = make_server()
        spawned.append(s)
        router.add_backend("127.0.0.1", s.port)
        return True

    scaler = Autoscaler(
        ServingSignalSource(router.healthz),
        scale_up,
        lambda: False,  # this leg only grows; drain is soak-proven
        min_replicas=1,
        max_replicas=2,
        interval_s=0.2,
        samples=2,
        cooldown_s=1.0,
        up_load=0.7,
        down_load=0.1,
    )
    try:
        load = Load(router.port, interactive_conns + bulk_conns,
                    max(interactive_window, 8), "web",
                    "interactive").start()
        ok0 = router.healthz()["replies_ok"]
        time.sleep(scale_window_s)
        rps1 = (router.healthz()["replies_ok"] - ok0) / scale_window_s
        scaler.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if router.healthz()["admitted"] >= 2:
                break
            time.sleep(0.1)
        admitted = router.healthz()["admitted"]
        time.sleep(0.5)  # settle: let dispatch spread onto the new replica
        ok0 = router.healthz()["replies_ok"]
        time.sleep(scale_window_s)
        rps2 = (router.healthz()["replies_ok"] - ok0) / scale_window_s
        final = load.finish()
        h = router.healthz()
        out["autoscale_scaling"] = {
            "rps_1_replica": round(rps1, 2),
            "rps_2_replicas": round(rps2, 2),
            "scaling_2_over_1": round(rps2 / rps1, 3) if rps1 else None,
            "admitted_after_scale": admitted,
            "scale_ups": scaler.snapshot()["scale_ups"],
            "identity_ok": (
                final["identity_ok"]
                and h["requests_total"] == h["answered_total"]
            ),
        }
    finally:
        scaler.close()
        router.drain()
        for s in servers + spawned:
            s.drain()
    return out


def bench_torch_cpu_baseline() -> float:
    """Reference-style D4PG step: CPU torch nets + host NumPy projection."""
    import torch
    import torch.nn as nn

    # Pinned: single-threaded — the host has one core, and letting torch
    # guess made the measured baseline drift run-to-run (VERDICT round-1
    # weak #5).
    torch.set_num_threads(1)

    class TActor(nn.Module):
        def __init__(self):
            super().__init__()
            self.net = nn.Sequential(
                nn.Linear(OBS_DIM, HIDDEN), nn.ReLU(),
                nn.Linear(HIDDEN, HIDDEN), nn.ReLU(),
                nn.Linear(HIDDEN, HIDDEN), nn.ReLU(),
                nn.Linear(HIDDEN, ACT_DIM), nn.Tanh(),
            )

        def forward(self, x):
            return self.net(x)

    class TCritic(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(OBS_DIM, HIDDEN)
            self.fc2 = nn.Linear(HIDDEN + ACT_DIM, HIDDEN)
            self.fc3 = nn.Linear(HIDDEN, HIDDEN)
            self.head = nn.Linear(HIDDEN, ATOMS)

        def forward(self, s, a):
            x = torch.relu(self.fc1(s))
            x = torch.relu(self.fc2(torch.cat([x, a], -1)))
            x = torch.relu(self.fc3(x))
            return torch.softmax(self.head(x), -1)

    actor, critic = TActor(), TCritic()
    actor_t, critic_t = TActor(), TCritic()
    actor_t.load_state_dict(actor.state_dict())
    critic_t.load_state_dict(critic.state_dict())
    opt_a = torch.optim.Adam(actor.parameters(), lr=1e-4)
    opt_c = torch.optim.Adam(critic.parameters(), lr=1e-4)
    z = np.linspace(V_MIN, V_MAX, ATOMS)
    delta = (V_MAX - V_MIN) / (ATOMS - 1)
    zt = torch.tensor(z, dtype=torch.float32)

    rng = np.random.default_rng(0)
    obs = torch.tensor(rng.normal(size=(BATCH, OBS_DIM)), dtype=torch.float32)
    act = torch.tensor(rng.uniform(-1, 1, size=(BATCH, ACT_DIM)), dtype=torch.float32)
    rew = rng.uniform(-1, 0, size=BATCH)
    nobs = torch.tensor(rng.normal(size=(BATCH, OBS_DIM)), dtype=torch.float32)
    disc = np.full(BATCH, 0.99)

    def one_step():
        with torch.no_grad():
            na = actor_t(nobs)
            tp = critic_t(nobs, na).numpy()  # host hop like ddpg.py:214
        # vectorized NumPy projection (reference's own vectorized form)
        tz = np.clip(rew[:, None] + disc[:, None] * z[None, :], V_MIN, V_MAX)
        b = (tz - V_MIN) / delta
        lo, hi = np.floor(b).astype(int), np.ceil(b).astype(int)
        m = np.zeros_like(tp)
        eq = lo == hi
        np.add.at(m, (np.arange(BATCH)[:, None], lo), tp * (np.where(eq, 1.0, hi - b)))
        np.add.at(m, (np.arange(BATCH)[:, None], hi), tp * (b - lo))
        mt = torch.tensor(m, dtype=torch.float32)
        pred = critic(obs, act)
        closs = -(mt * torch.log(pred + 1e-10)).sum(-1).mean()
        opt_c.zero_grad()
        closs.backward()
        opt_c.step()
        a = actor(obs)
        aloss = -(critic(obs, a) * zt).sum(-1).mean()
        opt_a.zero_grad()
        aloss.backward()
        opt_a.step()
        with torch.no_grad():
            for t, s in zip(actor_t.parameters(), actor.parameters()):
                t.mul_(0.999).add_(0.001 * s)
            for t, s in zip(critic_t.parameters(), critic.parameters()):
                t.mul_(0.999).add_(0.001 * s)

    for _ in range(5):
        one_step()
    t0 = time.perf_counter()
    for _ in range(BASELINE_MEASURE_STEPS):
        one_step()
    dt = time.perf_counter() - t0
    return BASELINE_MEASURE_STEPS / dt


def _cpu_fallback_host_pipeline() -> dict:
    """Clearly-marked CPU-backend host-pipeline numbers for when the TPU is
    unreachable (``--allow-cpu-fallback``): the host data-plane stages
    (sample/gather/stage/write-back) are chip-independent host CPU work, so
    legacy-vs-block comparisons stay meaningful; only train_dispatch and
    the steps/s headline reflect the CPU stand-in device."""
    line = {
        "metric": "host_pipeline_cpu_fallback",
        "backend": "cpu_fallback",
        "note": "TPU unreachable; host data-plane stages measured on the "
        "CPU backend — host_ms_per_dispatch is chip-independent, "
        "steps_per_sec is NOT a TPU number",
    }
    # Reduced shapes: the CPU stand-in device would otherwise dominate the
    # wall clock (batch-256 3×256 CPU jit steps); the HOST stages stay
    # representative, and benchmarks/host_pipeline_microbench.json is the
    # committed full comparison.
    for name, kw in (
        ("legacy_k1", dict(sampler="legacy", k=1, steps=60)),
        ("block_k1", dict(sampler="block", k=1, steps=60)),
        ("legacy_k8", dict(sampler="legacy", k=8, steps=30)),
        ("block_k8", dict(sampler="block", k=8, steps=30)),
    ):
        line[name] = bench_host_pipeline(
            prefetch=False, compute_dtype="float32", rows=16_384,
            batch=128, hidden=64, **kw
        )
    for kk in ("k1", "k8"):
        legacy = line[f"legacy_{kk}"]["host_ms_per_dispatch"]
        block = line[f"block_{kk}"]["host_ms_per_dispatch"]
        if legacy > 0:
            line[f"host_ms_ratio_{kk}"] = round(block / legacy, 4)
    return line


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--allow-cpu-fallback",
        action="store_true",
        help="when the TPU is unreachable, still emit clearly-marked "
        "CPU-backend host-pipeline numbers (a second JSON line) after the "
        "structured tpu_unreachable line",
    )
    ap.add_argument(
        "--serve",
        action="store_true",
        help="run the serving load generator (bench_serve: closed-loop "
        "single-vs-saturated throughput + open-loop shed/latency per load "
        "level) against an in-process policy server on the current "
        "backend, print ONE JSON line, and exit; the committed "
        "chip-independent artifact is benchmarks/serve_microbench.json",
    )
    ap.add_argument(
        "--serve-router",
        action="store_true",
        help="run the replica front-end load generator (bench_serve_router: "
        "aggregate throughput + p99 across 1 vs 2 in-process replicas, and "
        "availability/accounting identity during an abrupt replica kill), "
        "print ONE JSON line, and exit; the committed chip-independent "
        "artifact is benchmarks/router_microbench.json",
    )
    ap.add_argument(
        "--serve-multitenant",
        action="store_true",
        help="run the multi-tenant load generator (bench_serve_multitenant: "
        "interactive p99 alone vs under a flooding bulk tenant through the "
        "router's class-aware admission, and aggregate rps at 1 vs "
        "autoscaled 2 replicas), print ONE JSON line, and exit; the "
        "committed chip-independent artifact is "
        "benchmarks/multitenant_microbench.json",
    )
    args = ap.parse_args(argv)
    # Hermetic gate: the driver must get ONE parseable JSON line even when
    # the TPU tunnel is wedged (raises, hangs, or silently downgrades to
    # the CPU backend — all three observed). Probe in a subprocess before
    # any jax call here; an accelerator-less default backend only counts
    # when the user explicitly asked for it via JAX_PLATFORMS=cpu.
    platform = _probe_default_backend()
    explicit_cpu = os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    if platform is None or (platform == "cpu" and not explicit_cpu):
        detail = (
            "default JAX backend failed to initialize (subprocess probe)"
            if platform is None
            else "accelerator plugin failed to initialize; jax fell back "
            "to the cpu backend"
        )
        print(
            json.dumps(
                {
                    "error": "tpu_unreachable",
                    "metric": "learner_grad_steps_per_sec",
                    "value": None,
                    "detail": detail
                    + " — set JAX_PLATFORMS=cpu for a deliberate CPU run; "
                    "benchmarks/fused_microbench.py is the chip-independent "
                    "regression smoke"
                    + (
                        ""
                        if args.allow_cpu_fallback
                        else "; pass --allow-cpu-fallback for CPU-backend "
                        "host-pipeline numbers"
                    ),
                }
            )
        )
        if args.allow_cpu_fallback:
            # Fresh subprocess with JAX_PLATFORMS=cpu rather than setting
            # it in-process: after the (killed) probe child has touched
            # this image's libtpu, a same-process jax import crawls
            # through its 30-retry GCP-metadata fetches even on the cpu
            # platform (measured: minutes); a clean child env sidesteps
            # that wedge entirely — the same hermetic discipline as the
            # probe itself.
            import subprocess
            import sys

            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import json, bench; "
                    "print(json.dumps(bench._cpu_fallback_host_pipeline()))",
                ],
                capture_output=True,
                text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=1800,
            )
            out = [
                ln for ln in proc.stdout.strip().splitlines()
                if ln.startswith("{")
            ]
            if proc.returncode == 0 and out:
                print(out[-1])
            else:
                print(
                    json.dumps(
                        {
                            "metric": "host_pipeline_cpu_fallback",
                            "error": "cpu_fallback_failed",
                            "detail": proc.stderr.strip()[-400:],
                        }
                    )
                )
        return
    # --serve runs AFTER the hermetic gate on purpose: bench_serve
    # initializes the backend in-process, which on a wedged tunnel raises,
    # hangs, or silently downgrades (the exact failure modes the probe
    # exists to intercept). A deliberate CPU run is JAX_PLATFORMS=cpu.
    if args.serve:
        out = bench_serve()
        out["metric"] = "serve_loadgen"
        import jax

        out["backend"] = jax.default_backend()
        print(json.dumps(out))
        return
    if args.serve_router:
        out = bench_serve_router()
        out["metric"] = "serve_router_loadgen"
        import jax

        out["backend"] = jax.default_backend()
        print(json.dumps(out))
        return
    if args.serve_multitenant:
        out = bench_serve_multitenant()
        out["metric"] = "serve_multitenant_loadgen"
        import jax

        out["backend"] = jax.default_backend()
        print(json.dumps(out))
        return
    tpu = bench_tpu()
    # bf16 flagship line (same program, bf16 matmuls): the repo's own
    # measurement says bf16 is 0-30% faster at these shapes, and the MFU
    # denominator is the bf16 peak — so the f32-only number was
    # conservative twice over (VERDICT round-3 weak #4).
    bf16 = bench_tpu(compute_dtype="bfloat16")
    # Fused Pallas projection+loss kernel (projection_backend=pallas_fused):
    # same protocol, both dtypes — the byte-reduction claim is committed as
    # fused-vs-unfused steps/s AND XLA-accounted bytes from the same runs.
    fused_f32 = bench_tpu(projection_backend="pallas_fused")
    fused_bf16 = bench_tpu(
        compute_dtype="bfloat16", projection_backend="pallas_fused"
    )
    # Host replay→device pipeline with and without the double buffer
    # (legacy sampler: the prefetch comparison stays apples-to-apples with
    # the round-6 numbers), plus the native batched block sampler — the
    # round-7 host data-plane under test.
    pipe_off = bench_host_pipeline(prefetch=False)
    pipe_on = bench_host_pipeline(prefetch=True)
    pipe_block = bench_host_pipeline(prefetch=False, sampler="block")
    # Device-resident replay + fused megastep at the flagship K=32 shape
    # (runtime/megastep.py): the zero-transfer learner loop, next to the
    # host pipeline it replaces — transfer bytes are counted, not prose.
    mega_dev = bench_megastep(placement="device", k=32, steps=16)
    mega_hyb = bench_megastep(placement="hybrid", k=32, steps=16)
    # f32 on purpose: the megastep variants above run f32, and a bf16
    # host line would fold the dtype speedup into the data-plane delta.
    pipe_k32 = bench_host_pipeline(
        prefetch=False, sampler="block", k=32, compute_dtype="float32"
    )
    baseline = bench_torch_cpu_baseline()
    # The headline AND its utilization/roofline numbers come from the SAME
    # (winning) run — pairing a bf16 throughput with f32-program bytes/flops
    # would make value × flops ≠ achieved_tflops. The fused-kernel variants
    # compete for the headline on equal protocol footing.
    candidates = [
        (tpu, "float32", "xla"),
        (bf16, "bfloat16", "xla"),
        (fused_f32, "float32", "pallas_fused"),
        (fused_bf16, "bfloat16", "pallas_fused"),
    ]
    winner, headline_dtype, headline_projection = max(
        candidates, key=lambda c: c[0]["steps_per_sec"]
    )
    line = {
        "metric": "learner_grad_steps_per_sec",
        "value": round(winner["steps_per_sec"], 2),
        "unit": "steps/s",
        "vs_baseline": round(winner["steps_per_sec"] / baseline, 2),
        "baseline_steps_per_sec": round(baseline, 2),
        "headline_dtype": headline_dtype,
        "headline_projection": headline_projection,
        "f32_steps_per_sec": round(tpu["steps_per_sec"], 2),
        "bf16_steps_per_sec": round(bf16["steps_per_sec"], 2),
        # Fused-vs-unfused block: steps/s plus XLA-accounted bytes from the
        # SAME runs, so the kernel's byte cut is a committed artifact.
        "fused_f32_steps_per_sec": round(fused_f32["steps_per_sec"], 2),
        "fused_bf16_steps_per_sec": round(fused_bf16["steps_per_sec"], 2),
        # Host replay→device pipeline, double buffer off/on: the delta is
        # the host-sampling + H2D share of the critical path.
        "prefetch_off_steps_per_sec": round(pipe_off["steps_per_sec"], 2),
        "prefetch_on_steps_per_sec": round(pipe_on["steps_per_sec"], 2),
        "prefetch_speedup": round(
            pipe_on["steps_per_sec"] / pipe_off["steps_per_sec"], 3
        ),
        # Per-stage host time per dispatch (ms), legacy vs the native
        # batched block sampler — the round-7 measured claim; the same
        # stage names appear in every training run's metrics.jsonl.
        "host_stage_ms_legacy": pipe_off["stage_ms_per_dispatch"],
        "host_stage_ms_block": pipe_block["stage_ms_per_dispatch"],
        "host_ms_per_dispatch_legacy": pipe_off["host_ms_per_dispatch"],
        "host_ms_per_dispatch_block": pipe_block["host_ms_per_dispatch"],
        "host_tree_backend": pipe_block["tree_backend"],
        # Per-grad-step link traffic, counted from the exact arrays each
        # loop stages H2D / fetches D2H (see docs/data_plane.md): the
        # host path's number is what the megastep exists to zero out, so
        # the zero-transfer claim is a regression-checked number here,
        # not prose. All three at the flagship K=32 dispatch shape.
        "transfer_bytes_per_grad_step_host": pipe_k32[
            "transfer_bytes_per_grad_step"
        ],
        "transfer_bytes_per_grad_step_hybrid": mega_hyb[
            "transfer_bytes_per_grad_step"
        ],
        "transfer_bytes_per_grad_step_megastep": mega_dev[
            "transfer_bytes_per_grad_step"
        ],
        "megastep_steps_per_sec": round(mega_dev["steps_per_sec"], 2),
        "hybrid_steps_per_sec": round(mega_hyb["steps_per_sec"], 2),
        "host_k32_steps_per_sec": round(pipe_k32["steps_per_sec"], 2),
    }
    if "mfu" in mega_dev:
        line["megastep_mfu"] = round(mega_dev["mfu"], 5)
    # Sharded megastep (ROADMAP item 2): same shape over the whole device
    # ring, when the backend has one. Transfer bytes stay 0 — the
    # zero-transfer steady state surviving scale-out is the claim; the
    # full dp=1-vs-dp>1 artifact is benchmarks/shard_microbench.json.
    import jax as _jax

    n_dev = _jax.device_count()
    # Guard, don't crash: batch/rows/capacity must divide dp (a 6-device
    # box would otherwise abort the whole suite after every earlier point
    # already ran); the committed artifact covers the full claim.
    if n_dev > 1 and BATCH % n_dev == 0 and 65_536 % n_dev == 0:
        mega_sharded = bench_megastep(
            placement="device", k=32, steps=8, dp=n_dev
        )
        line["sharded_megastep_dp"] = n_dev
        line["sharded_megastep_steps_per_sec"] = round(
            mega_sharded["steps_per_sec"], 2
        )
        line["transfer_bytes_per_grad_step_sharded"] = mega_sharded[
            "transfer_bytes_per_grad_step"
        ]
    if pipe_off["host_ms_per_dispatch"] > 0:
        line["host_ms_ratio_block_over_legacy"] = round(
            pipe_block["host_ms_per_dispatch"] / pipe_off["host_ms_per_dispatch"],
            4,
        )
    if "bytes_per_grad_step" in bf16 and "bytes_per_grad_step" in fused_bf16:
        line["unfused_bytes_per_grad_step"] = round(bf16["bytes_per_grad_step"])
        line["fused_bytes_per_grad_step"] = round(
            fused_bf16["bytes_per_grad_step"]
        )
        line["fused_bytes_ratio"] = round(
            fused_bf16["bytes_per_grad_step"] / bf16["bytes_per_grad_step"], 4
        )
    # MFU block (when XLA cost analysis + a known chip peak are available).
    # Single-digit MFU is EXPECTED here and stated as such: the flagship
    # model is 3×256 MLPs at batch 256 — the per-step matmuls are far below
    # MXU-saturating sizes and the random pool gather dominates (see
    # benchmarks/projection_bench.py for the compute-only ceiling and
    # benchmarks/mfu_sweep.py for where the same framework's MFU lands
    # with MXU-saturating shapes).
    if "achieved_tflops" in winner:
        line["flops_per_grad_step"] = round(winner["flops_per_grad_step"])
        line["achieved_tflops"] = round(winner["achieved_tflops"], 3)
    if "mfu" in winner:
        line["peak_tflops"] = winner["peak_tflops"]
        line["mfu"] = round(winner["mfu"], 5)
    if "mfu" in tpu:
        line["f32_mfu"] = round(tpu["mfu"], 5)
    if "mfu" in bf16:
        line["bf16_mfu"] = round(bf16["mfu"], 5)
    if "mfu" in fused_bf16:
        line["fused_bf16_mfu"] = round(fused_bf16["mfu"], 5)
    if "xla_bytes_util" in fused_bf16:
        line["fused_xla_bytes_util"] = round(fused_bf16["xla_bytes_util"], 4)
    # Roofline block: the falsifiable form of "the gather, not the MXU, is
    # the bottleneck" — achieved HBM GB/s vs the chip's peak, same run as
    # the headline.
    if "achieved_gbps" in winner:
        line["bytes_per_grad_step"] = round(winner["bytes_per_grad_step"])
        line["achieved_gbps"] = round(winner["achieved_gbps"], 1)
        if "peak_gbps" in winner:
            line["peak_gbps"] = winner["peak_gbps"]
            line["xla_bytes_util"] = round(winner["xla_bytes_util"], 4)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
