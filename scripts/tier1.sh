#!/usr/bin/env bash
# Tier-1 verify — static gate first, then the ROADMAP.md test command,
# verbatim, so humans and tooling run the exact same pytest gate. Prints
# DOTS_PASSED=<n> at the end and exits with pytest's status (lint
# failures exit immediately before pytest runs).
#
# Usage: scripts/tier1.sh   (from the repo root or anywhere inside it)
cd "$(dirname "$0")/.." || exit 1
scripts/lint.sh || exit 1
# Clock guard: report the 20 slowest tests every run (via PYTEST_ADDOPTS so
# the ROADMAP gate line below stays verbatim). PR 9 measured 560 s of the
# 870 s budget — when a suite creeps, this names the offender; anything new
# past ~10 s belongs behind the `slow` marker (the multitenant microbench
# smoke additionally asserts its own stated budget).
export PYTEST_ADDOPTS="--durations=20 ${PYTEST_ADDOPTS:-}"
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
