"""A deterministic stand-in learner for league-controller tests/smokes.

Speaks exactly the league-relevant surface of ``train.py`` — nothing
else — so the controller's WHOLE lifecycle (spawn, SIGTERM→exit-75
drain, manifest-attested checkpoints, fork-resume with verify-on-restore
fallback, trainer_meta attestation, metrics/best_eval fitness) runs in
milliseconds instead of JAX-import seconds:

- checkpoints: ``checkpoints/<step>/params.bin`` + the REAL commit-record
  manifest (``d4pg_tpu.runtime.manifest`` — the same digests the real
  ``restore_verified`` checks), trainer_meta.json stamped with
  variant_id/league_generation (the controller's fork attestation);
- resume: newest INTACT step wins; a truncated newest fork (the
  ``clone_corrupt`` chaos) logs a ``[checkpoint] fallback`` and restores
  the older copied step — never the torn one;
- fitness: deterministic in the GENOME —
  ``100 − 20·|log10(lr_actor/1e-4)| − 0.2·max_episode_steps`` (+ a tiny
  seeded jitter) — so "the planted better variant wins" is a provable
  claim, not a training-noise hope;
- SIGTERM → final checkpoint → exit 75 (the preemption contract);
- poison knobs for the failure paths: ``--stub-no-checkpoint`` (attest
  timeout → rollback), ``--stub-crash-after N`` (supervisor restart /
  quarantine).

Run from the repo root (imports d4pg_tpu; stdlib-only modules).
"""

import argparse
import json
import math
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from d4pg_tpu.runtime import manifest as ckpt_manifest  # noqa: E402


def parse_args(argv):
    p = argparse.ArgumentParser()
    p.add_argument("--log-dir", required=True)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--variant-id", type=int, default=0)
    p.add_argument("--league-generation", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--total-steps", type=int, default=10**9)
    p.add_argument("--checkpoint-interval", type=int, default=4)
    p.add_argument("--eval-interval", type=int, default=2)
    p.add_argument("--lr-actor", type=float, default=1e-4)
    p.add_argument("--lr-critic", type=float, default=1e-4)
    p.add_argument("--noise-epsilon", type=float, default=0.3)
    p.add_argument("--tau", type=float, default=0.001)
    p.add_argument("--max-steps", type=int, default=200)
    p.add_argument("--bsize", type=int, default=8)
    p.add_argument("--n-step", type=int, default=3)
    p.add_argument("--tick-seconds", type=float, default=0.05)
    p.add_argument("--stub-no-checkpoint", action="store_true")
    p.add_argument("--stub-crash-after", type=int, default=0)
    args, _unknown = p.parse_known_args(argv)
    return args


def fitness(args, step):
    base = 100.0 - 20.0 * abs(math.log10(args.lr_actor / 1e-4))
    base -= 0.2 * args.max_steps
    jitter = (((args.seed * 1103515245 + step * 12345) >> 8) % 1000) / 1e4
    return base + jitter


def atomic(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def save_checkpoint(args, ckpt_dir, step):
    step_dir = os.path.join(ckpt_dir, str(step))
    os.makedirs(step_dir, exist_ok=True)
    # params derived from genome+step: forks carry real, checkable bytes
    with open(os.path.join(step_dir, "params.bin"), "wb") as f:
        f.write(
            f"lr={args.lr_actor} tau={args.tau} step={step}".encode() * 64
        )
    meta_path = os.path.join(ckpt_dir, "trainer_meta.json")
    atomic(meta_path, {
        "env_steps": step * 10,
        "ewma_return": fitness(args, step),
        "variant_id": args.variant_id,
        "league_generation": args.league_generation,
    })
    # commit record LAST — the real write-ordering discipline
    ckpt_manifest.write_manifest_file(
        ckpt_manifest.manifest_path(ckpt_dir, step),
        ckpt_manifest.build_manifest(step, step_dir, [meta_path]),
    )


def restore(ckpt_dir):
    steps = ckpt_manifest.manifest_steps(ckpt_dir)
    for step in sorted(steps, reverse=True):
        ok, why, _warn = ckpt_manifest.verify_step_dir(
            ckpt_dir, step, ckpt_manifest.default_step_dir(ckpt_dir, step)
        )
        if not ok:
            print(f"[checkpoint] fallback: step {step}: {why}", flush=True)
            continue
        print(f"[checkpoint] resumed from step {step}", flush=True)
        return step
    if steps:
        print("[checkpoint] no intact step; starting fresh", flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv if argv is not None else sys.argv[1:])
    run = args.log_dir
    ckpt_dir = os.path.join(run, "checkpoints")
    os.makedirs(ckpt_dir, exist_ok=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    # simulated divergence: an absurd actor lr "NaNs out" on the first
    # tick, before any eval lands — the deterministic crash-loop that
    # proves quarantine (a crasher WITH fitness is culled by PBT instead,
    # which is also correct, but not the path this knob exists to pin)
    crash_after = args.stub_crash_after or (1 if args.lr_actor >= 0.5 else 0)
    step = restore(ckpt_dir) if args.resume else 0
    print(f"[stub-learner] v{args.variant_id} step={step} "
          f"lr={args.lr_actor} max_steps={args.max_steps}", flush=True)
    metrics = open(os.path.join(run, "metrics.jsonl"), "a")
    t0 = time.monotonic()
    while step < args.total_steps and not stop:
        time.sleep(args.tick_seconds)
        step += 1
        if crash_after and step >= crash_after:
            print("[stub-learner] poison crash", flush=True)
            sys.exit(3)
        if step % args.eval_interval == 0:
            score = fitness(args, step)
            row = {
                "step": step,
                "t": round(time.monotonic() - t0, 4),
                "eval_return_mean": score,
                "avg_test_reward_ewma": score,
                "variant_id": float(args.variant_id),
                "league_generation": float(args.league_generation),
            }
            metrics.write(json.dumps(row) + "\n")
            metrics.flush()
            atomic(os.path.join(run, "best_eval.json"), {
                "step": step, "eval_return_mean": score,
                "env_steps": step * 10,
            })
        if step % args.checkpoint_interval == 0 and not args.stub_no_checkpoint:
            save_checkpoint(args, ckpt_dir, step)
    if stop:
        if not args.stub_no_checkpoint:
            save_checkpoint(args, ckpt_dir, step)
        print("[stub-learner] preempted: checkpointed, exiting 75",
              flush=True)
        sys.exit(75)
    print("[stub-learner] done", flush=True)
    sys.exit(0)


if __name__ == "__main__":
    main()
