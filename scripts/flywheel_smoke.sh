#!/usr/bin/env bash
# Flywheel smoke: the closed data loop end to end through the REAL CLIs
# (docs/flywheel.md) — served traffic becomes training data. Wired into
# tier-1 via tests/test_flywheel_smoke.py; also runnable by hand:
#
#   scripts/flywheel_smoke.sh                  # throwaway run dir
#   FLYWHEEL_SMOKE_DIR=/tmp/x scripts/flywheel_smoke.sh
#
# The flow:
#   1. train.py --fleet-listen 0 --num-envs 0 --debug-guards: the learner
#      runs the ingest server with NO local collection and NO fleet
#      actors — it can only finish if the MIRROR supplies real windows
#      (fleet pacing proves the tap end to end);
#   2. python -m d4pg_tpu.serve serves the learner-published bundle with
#      --mirror-fraction 1.0, streaming every served episode's windows
#      to the ingest AND spooling them on disk;
#   3. the sim client plays env episodes through the serve path, echoing
#      reward/done + behavior log-prob on FEEDBACK frames;
#   4. learner completes rc 0 (paced purely by mirrored traffic); a
#      fixed-seed evaluator run (--noise-sigma 0 --no-feedback, pure v1
#      ACT) then proves the v1 sublanguage still round-trips on the same
#      server; SIGTERM-drain the server and audit the books:
#      every ingested window came from source=mirror, the tap's window
#      accounting identity is exact, and the spool decodes with the
#      behavior-log-prob column the promotion gate needs.
#
# Knobs (env vars): FLYWHEEL_SMOKE_DIR, FLYWHEEL_SMOKE_STEPS (default
# 60), FLYWHEEL_SMOKE_HIDDEN (default 16,16).
set -euo pipefail
cd "$(dirname "$0")/.."

RUN=${FLYWHEEL_SMOKE_DIR:-$(mktemp -d /tmp/flywheel_smoke.XXXXXX)}
mkdir -p "$RUN"
STEPS=${FLYWHEEL_SMOKE_STEPS:-60}
HIDDEN=${FLYWHEEL_SMOKE_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

echo "[flywheel-smoke] run dir: $RUN"

python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --total-steps "$STEPS" --warmup 24 --bsize 8 --rmsize 512 \
  --eval-interval "$STEPS" --eval-episodes 2 \
  --checkpoint-interval "$STEPS" --num-envs 0 \
  --fleet-listen 0 --fleet-bundle "$RUN/bundle" \
  --fleet-publish-interval 20 --debug-guards \
  --log-dir "$RUN" > "$RUN/learner.log" 2>&1 &
LEARNER=$!

PORT=
for _ in $(seq 1 600); do
  PORT=$(sed -n 's/.*ingest listening on :\([0-9][0-9]*\).*/\1/p' "$RUN/learner.log" | head -1)
  if [ -n "$PORT" ] && [ -f "$RUN/bundle/bundle.json" ]; then break; fi
  kill -0 "$LEARNER" 2>/dev/null \
    || { cat "$RUN/learner.log"; echo "FLYWHEEL_SMOKE_FAIL: learner died before listening"; exit 1; }
  sleep 0.2
done
[ -n "$PORT" ] || { cat "$RUN/learner.log"; echo "FLYWHEEL_SMOKE_FAIL: no ingest port"; exit 1; }
echo "[flywheel-smoke] ingest on :$PORT"

python -m d4pg_tpu.serve --bundle "$RUN/bundle" --port 0 \
  --max-batch 8 --max-wait-us 500 --debug-guards \
  --mirror-fraction 1.0 --mirror-ingest "127.0.0.1:$PORT" \
  --mirror-spool "$RUN/spool" > "$RUN/server.log" 2>&1 &
SERVER=$!

SPORT=
for _ in $(seq 1 600); do
  SPORT=$(sed -n 's/.*listening on 127\.0\.0\.1:\([0-9][0-9]*\).*/\1/p' "$RUN/server.log" | head -1)
  [ -n "$SPORT" ] && break
  kill -0 "$SERVER" 2>/dev/null \
    || { cat "$RUN/server.log"; echo "FLYWHEEL_SMOKE_FAIL: server died before listening"; exit 1; }
  sleep 0.2
done
[ -n "$SPORT" ] || { cat "$RUN/server.log"; echo "FLYWHEEL_SMOKE_FAIL: no serve port"; exit 1; }
echo "[flywheel-smoke] serving on :$SPORT"

# Served traffic with reward echo: short truncated episodes so windows
# flow continuously while the learner paces through its steps.
python -m d4pg_tpu.flywheel.sim_client --connect "127.0.0.1:$SPORT" \
  --env Pendulum-v1 --episodes 500 --seed 7 --noise-sigma 0.3 \
  --max-steps 25 > "$RUN/sim.log" 2>&1 &
SIM=$!

# The learner can only complete because the MIRROR feeds it (there are
# no actors and no local envs): its rc 0 IS the closed-loop proof, and
# --debug-guards means any recompile/transfer/staging trip raised.
if ! wait "$LEARNER"; then
  cat "$RUN/learner.log"; kill -9 "$SIM" "$SERVER" 2>/dev/null || true
  echo "FLYWHEEL_SMOKE_FAIL: learner exited non-zero"; exit 1
fi
kill -TERM "$SIM" 2>/dev/null || true
wait "$SIM" 2>/dev/null || true

# The v1 sublanguage must survive the flywheel: a fixed-seed evaluator
# run over plain v1 ACT frames (no feedback, nothing mirrored) against
# the SAME server that just carried FEEDBACK traffic.
python -m d4pg_tpu.flywheel.sim_client --connect "127.0.0.1:$SPORT" \
  --env Pendulum-v1 --episodes 1 --seed 3 --noise-sigma 0 \
  --no-feedback --max-steps 20 > "$RUN/eval.log" 2>&1 \
  || { cat "$RUN/eval.log"; echo "FLYWHEEL_SMOKE_FAIL: v1 evaluator run failed"; exit 1; }
grep -q "SIM_CLIENT_OK" "$RUN/eval.log" \
  || { cat "$RUN/eval.log"; echo "FLYWHEEL_SMOKE_FAIL: evaluator never finished"; exit 1; }

kill -TERM "$SERVER"
if ! wait "$SERVER"; then
  cat "$RUN/server.log"; echo "FLYWHEEL_SMOKE_FAIL: server drain exited non-zero"; exit 1
fi
grep -q "\[serve\] mirror:" "$RUN/server.log" \
  || { cat "$RUN/server.log"; echo "FLYWHEEL_SMOKE_FAIL: server never printed mirror books"; exit 1; }

# The books: every ingested window came from the mirror (per-source
# split — the split identity itself is asserted at ingest close by the
# learner's --debug-guards ConservationLedger), the tap's window
# accounting identity and the server's admitted-request identity are
# exact (the tap-close / serve-drain [flow-verdict] lines), and the
# spool holds gate-readable frames with the behavior-log-prob column.
python - "$RUN" <<'EOF'
import json, sys
run = sys.argv[1]
rows = [json.loads(l) for l in open(f"{run}/metrics.jsonl")]
fleet = [r for r in rows if "fleet_windows_ingested" in r]
assert fleet, "no metrics row carries fleet counters"
last = fleet[-1]
assert last["fleet_windows_ingested"] > 0, last
assert last["fleet_windows_from_mirror"] > 0, last
assert last["fleet_windows_from_actors"] == 0, last


def verdicts(log, family):
    out = [json.loads(l.split("[flow-verdict]", 1)[1])
           for l in open(f"{run}/{log}") if "[flow-verdict]" in l]
    return [v for v in out if v["family"] == family]


# learner close: windows_from_actors + windows_from_mirror == ingested
fi = verdicts("learner.log", "fleet-ingest")
assert fi and all(v["ok"] for v in fi), fi
# server drain: every admitted request resolved ok/shed, inflight 0
ss = verdicts("server.log", "serve-stats")
assert ss and all(v["ok"] for v in ss), ss
# tap close: every built window acked/stale/shed/dropped-with-a-reason
mt = verdicts("server.log", "mirror-tap")
assert mt and all(v["ok"] for v in mt), mt

mline = [l for l in open(f"{run}/server.log") if "[serve] mirror:" in l][-1]
tap = dict(kv.split("=") for kv in mline.split("mirror:", 1)[1].split())
tap = {k: int(v) for k, v in tap.items()}
assert tap["feedback_steps"] > 0 and tap["episodes_mirrored"] > 0, tap
assert tap["windows_acked"] > 0, tap

from d4pg_tpu.flywheel.spool import read_windows
cols, n = read_windows(f"{run}/spool", 3, 1)
assert n > 0 and "logprob" in cols and len(cols["logprob"]) == n, n
print("FLYWHEEL_SMOKE_COUNTERS_OK", {
    "ingested": last["fleet_windows_ingested"],
    "from_mirror": last["fleet_windows_from_mirror"],
    "tap_acked": tap["windows_acked"],
    "spooled": n,
})
EOF

echo "FLYWHEEL_SMOKE_OK"
