#!/usr/bin/env bash
# Repo lint gate: d4pglint (repo-specific invariants, zero findings
# required — per-file AST checks, the whole-program pass [lock-order
# graph, protocol conformance, thread lifecycle, unused suppressions],
# the docs-catalog drift check, and the shape-aware partition-rule
# coverage gate in a subprocess) + the benchmark/metrics JSON schema
# check (which also pins benchmarks/lock_order_graph.json acyclic and
# fresh). Wired into tier-1 both directly (scripts/tier1.sh runs this
# first) and as tests (tests/test_d4pglint.py::test_repo_lints_clean,
# tests/test_wholeprog.py), so the driver's verbatim ROADMAP pytest
# command enforces it too.
#
# The gate is also CLOCK-GUARDED (the tier-1 convention): the per-file
# pass fans out over a process pool (D4PGLINT_JOBS overrides the core
# count) and the whole run must finish inside LINT_BUDGET_S wall
# seconds — a lint gate nobody waits for is a lint gate nobody runs.
# Measured ~6s single-core; the default budget leaves slack for cold
# caches and loaded CI hosts.
#
# Usage: scripts/lint.sh            # lint the product-code manifest
#        scripts/lint.sh --show-suppressed   # audit the justifications
set -euo pipefail
cd "$(dirname "$0")/.."

LINT_BUDGET_S="${LINT_BUDGET_S:-120}"
SECONDS=0
python -m tools.d4pglint "$@"
python -m tools.d4pglint.schema_check
if (( SECONDS > LINT_BUDGET_S )); then
    echo "LINT_BUDGET_EXCEEDED: ${SECONDS}s > ${LINT_BUDGET_S}s — see the" \
         "[lint-timing] slowest-files line above" >&2
    exit 1
fi
echo "LINT_OK"
echo "LINT_WALL_S=${SECONDS} budget=${LINT_BUDGET_S}"
