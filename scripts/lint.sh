#!/usr/bin/env bash
# Repo lint gate: d4pglint (repo-specific AST invariants, zero findings
# required) + the benchmark/metrics JSON schema check. Wired into tier-1
# both directly (scripts/tier1.sh runs this first) and as a test
# (tests/test_d4pglint.py::test_repo_lints_clean), so the driver's
# verbatim ROADMAP pytest command enforces it too.
#
# Usage: scripts/lint.sh            # lint the product-code manifest
#        scripts/lint.sh --show-suppressed   # audit the justifications
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.d4pglint "$@"
python -m tools.d4pglint.schema_check
echo "LINT_OK"
