#!/usr/bin/env bash
# Repo lint gate: d4pglint (repo-specific invariants, zero findings
# required — per-file AST checks, the whole-program pass [lock-order
# graph, protocol conformance, thread lifecycle, unused suppressions],
# the docs-catalog drift check, and the shape-aware partition-rule
# coverage gate in a subprocess) + the benchmark/metrics JSON schema
# check (which also pins benchmarks/lock_order_graph.json acyclic and
# fresh). Wired into tier-1 both directly (scripts/tier1.sh runs this
# first) and as tests (tests/test_d4pglint.py::test_repo_lints_clean,
# tests/test_wholeprog.py), so the driver's verbatim ROADMAP pytest
# command enforces it too.
#
# Usage: scripts/lint.sh            # lint the product-code manifest
#        scripts/lint.sh --show-suppressed   # audit the justifications
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.d4pglint "$@"
python -m tools.d4pglint.schema_check
echo "LINT_OK"
