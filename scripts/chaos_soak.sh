#!/usr/bin/env bash
# Chaos soak: the self-healing contracts end to end through the REAL CLIs
# (docs/fault_tolerance.md). The loop:
#
#   1. train under injected faults (env crash + worker SIGKILL + flusher
#      stall) with --debug-guards — must exit 0 with restarts logged;
#   2. start a checkpointing run, kill -9 it at a random instant;
#   3. --resume — must come back rc 0 on the newest intact step (the
#      crash-consistency manifest contract), finishing the step budget;
#   4. export a bundle and serve it under an injected client socket
#      reset — server must answer before AND after, then drain on
#      SIGTERM with exit 0;
#   5. 2-process collection fleet under faults (docs/fleet.md): a
#      --fleet-listen learner with NO local collection + a remote actor
#      under partition / reconnect_flap / stale_bundle / slow_link
#      injections, then kill -9 the learner mid-run and --resume it on
#      the same port — the actor must reconnect under its bounded
#      Backoff and feed the resumed run to completion, with every
#      emitted window accounted for (acked/stale/shed/dropped — the
#      zero-torn-windows contract) and guards green throughout;
#   7. multi-tenant serving (ISSUE 12): mixed interactive+bulk load from
#      several tenants through the router's quota + class-aware
#      admission across two MULTI-POLICY replicas while (a) a bulk
#      tenant floods (chaos tenant_flood — interactive p99 must hold
#      inside its SLO, bulk sheds first, per-tenant accounting identity
#      exact), (b) traffic skews 95% onto one policy (chaos policy_skew
#      — the cold policy still meets its deadline), and (c) the
#      autoscaler scales UP under load then is chaos-forced to scale
#      DOWN mid-canary (scaledown_during_canary — the rollout must
#      abort or complete cleanly, never leaving a half-deployed bundle
#      dir anywhere, and every other policy's replicas end with
#      params_reloads == 0);
#   8. one data plane (ISSUE 13): a fleet-ONLY learner on a goal env
#      with --her --obs-norm (the cells the old refusal matrix closed)
#      fed by real actor hosts doing actor-side relabeling with
#      generation-tagged stats, under stale_stats (ingest must age the
#      stale-stats windows out with an honest count), pixel_truncate
#      (torn WINDOWS2 frame whole-drops), and her_actor_kill (SIGKILL
#      mid-episode; the restart reconnects) — learner rc 0 with guards
#      green and the at-most-once accounting identity exact;
#   9. league training (ISSUE 15): a seeded 3-variant PBT league of real
#      --debug-guards learners (fleet-only, one actor host per variant
#      pinned by the HELLO variant id) under variant_kill (learner group
#      SIGKILL → supervised restart), clone_corrupt (torn checkpoint
#      fork → verified-restore fallback), and a controller kill -9 the
#      moment a generation is in flight — the rerun must resume the SAME
#      generation, promote the planted winner's bloodline, keep every
#      accounting identity exact (process tenures, actor windows), end
#      with lockwitness 0 contradictions and zero surviving processes,
#      and emit the schema-gated league_soak.json artifact.
#  10. the flywheel (ISSUE 18): a fleet-only learner paced ENTIRELY by
#      the router's mirror tap (served traffic becomes training data,
#      logged propensities riding the frames), a promotion ladder where
#      every canary additionally needs the off-policy IS gate's verdict
#      over the mirrored windows, under gate_stall (first evaluation
#      wedges — bounded rollback, never a hang) and mirror_drop (tap
#      losses stay on the books); a planted collapsed bundle that serves
#      error-free must be gate-BLOCKED before live error rate sees it,
#      the fixed-seed served return must strictly rise across the soak,
#      both planes' accounting identities hold exact, and the leg emits
#      the schema-gated flywheel_soak.json artifact.
#  11. connection-level attack (ISSUE 20): router + two replicas all on
#      the netio event loop, under sustained real load, while the three
#      new chaos sites attack their OWN listeners — slowloris (trickled
#      bytes, never a frame), zero_window (pipelined floods, never
#      reads), fd_exhaust (descriptor-table hoard mid-accept). The
#      loops' read/write-progress deadlines must evict every attacker
#      (healthz netio counters prove it), interactive traffic must keep
#      answering throughout, the answered identity stays exact
#      ([flow-verdict] at drain), and every drain exits rc 0.
#
# Knobs (env vars): SOAK_DIR (default mktemp), SOAK_ENV (Pendulum-v1),
# SOAK_STEPS (grad steps per leg, default 6), SOAK_HIDDEN (16,16),
# SOAK_KILL_DELAY_MAX (seconds after first commit, default 2).
# Exits non-zero on the first broken contract.
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=${SOAK_DIR:-$(mktemp -d /tmp/chaos_soak.XXXXXX)}
mkdir -p "$DIR"
ENV_ID=${SOAK_ENV:-Pendulum-v1}
STEPS=${SOAK_STEPS:-6}
HIDDEN=${SOAK_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

common=(--env "$ENV_ID" --hidden-sizes "$HIDDEN" --warmup 24 --bsize 8
        --rmsize 512 --eval-interval 100000 --num-envs 2
        --pool-start-method fork --snapshot-replay)

echo "[chaos-soak] dir: $DIR"

# ---- leg 1: train THROUGH injected faults, guards on -----------------------
python train.py "${common[@]}" --log-dir "$DIR/faulty" \
  --total-steps "$STEPS" --checkpoint-interval "$STEPS" \
  --debug-guards --async-writeback --pool-step-timeout 15 \
  --chaos "seed=3;env_raise@5#0;worker_kill@9#1;wb_stall@1:0.2" \
  | tee "$DIR/faulty.log"
grep -q "worker_restart" "$DIR/faulty.log" \
  || { echo "CHAOS_SOAK_FAIL: no worker restart under injected faults"; exit 1; }
# the runtime lock-order witness ran and confirmed the committed static
# graph (a contradiction would have failed the run before this grep)
grep -q "\[lockwitness\].*0 contradictions" "$DIR/faulty.log" \
  || { echo "CHAOS_SOAK_FAIL: no lock-order witness verdict under --debug-guards"; exit 1; }

# ---- leg 2: kill -9 a checkpointing run at a random instant ----------------
python train.py "${common[@]}" --log-dir "$DIR/killed" \
  --total-steps 100000 --checkpoint-interval 4 \
  > "$DIR/killed.log" 2>&1 &
PID=$!
CKPT="$DIR/killed/checkpoints"
for _ in $(seq 1 600); do
  compgen -G "$CKPT/manifest_*.json" > /dev/null && break
  kill -0 "$PID" 2>/dev/null || { cat "$DIR/killed.log"; echo "CHAOS_SOAK_FAIL: run died before first commit"; exit 1; }
  sleep 0.5
done
compgen -G "$CKPT/manifest_*.json" > /dev/null \
  || { echo "CHAOS_SOAK_FAIL: no checkpoint committed"; exit 1; }
# randomized instant within the next interval: mid-save, mid-snapshot, between
sleep "0.$((RANDOM % 100))"; sleep "$((RANDOM % ${SOAK_KILL_DELAY_MAX:-2}))"
kill -9 "$PID" || true
wait "$PID" 2>/dev/null || true
echo "[chaos-soak] killed training at a random instant"

# ---- leg 3: resume must restore the newest intact step ---------------------
NEWEST=$(ls "$CKPT"/manifest_*.json | sed 's/.*manifest_\([0-9]*\).json/\1/' | sort -n | tail -1)
python train.py "${common[@]}" --log-dir "$DIR/killed" --resume \
  --total-steps $((NEWEST + 4)) --checkpoint-interval 4 \
  | tee "$DIR/resume.log"
grep -q "\[checkpoint\] resumed from step" "$DIR/resume.log" \
  || { echo "CHAOS_SOAK_FAIL: resume did not report its restored step"; exit 1; }

# ---- leg 4: serve the survivor under an injected socket reset --------------
python train.py --env "$ENV_ID" --hidden-sizes "$HIDDEN" \
  --log-dir "$DIR/killed" --export-bundle "$DIR/bundle"
python - "$DIR/bundle" <<'EOF'
import signal, subprocess, sys, numpy as np
bundle = sys.argv[1]
proc = subprocess.Popen(
    [sys.executable, "-m", "d4pg_tpu.serve", "--bundle", bundle,
     "--port", "0", "--max-batch", "8", "--max-wait-us", "500",
     "--chaos", "sock_reset@2"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
port = None
for line in proc.stdout:
    sys.stdout.write("[server] " + line)
    if "listening on" in line:
        port = int(line.split(":")[1].split()[0])
        break
assert port, "server never reported its port"
from d4pg_tpu.serve.client import PolicyClient
obs = np.array([0.1, -0.2, 0.05], np.float32)
with PolicyClient("127.0.0.1", port) as c:
    assert c.act(obs).shape == (1,)      # frame 1: served
    try:
        c.act(obs)                       # frame 2: injected reset
        raise SystemExit("CHAOS_SOAK_FAIL: injected reset never fired")
    except Exception:
        pass
with PolicyClient("127.0.0.1", port) as c:   # server survived the reset
    assert c.act(obs).shape == (1,)
    h = c.healthz()
    assert h.get("chaos_injections") == 1, h
proc.send_signal(signal.SIGTERM)
tail = proc.stdout.read()
sys.stdout.write("[server] " + tail)
rc = proc.wait(timeout=120)
assert rc == 0 and "drained" in tail, (rc, tail)
print("CHAOS_SOAK_SERVE_OK")
EOF

# ---- leg 5: collection fleet under faults + learner kill/resume ------------
FLEET_PORT=$((20000 + RANDOM % 20000))
FLEET_STEPS=${SOAK_FLEET_STEPS:-40}
fleet_learner=(--env "$ENV_ID" --hidden-sizes "$HIDDEN" --warmup 24 --bsize 8
               --rmsize 512 --eval-interval 100000 --num-envs 0
               --fleet-listen "$FLEET_PORT" --fleet-bundle "$DIR/fleet_bundle"
               --fleet-publish-interval 10 --debug-guards --snapshot-replay
               --log-dir "$DIR/fleet")

python train.py "${fleet_learner[@]}" --total-steps 100000 \
  --checkpoint-interval 8 --chaos "seed=5;partition@6" \
  > "$DIR/fleet_learner1.log" 2>&1 &
FLEARNER=$!
for _ in $(seq 1 600); do
  [ -f "$DIR/fleet_bundle/bundle.json" ] \
    && grep -q "ingest listening" "$DIR/fleet_learner1.log" && break
  kill -0 "$FLEARNER" 2>/dev/null \
    || { cat "$DIR/fleet_learner1.log"; echo "CHAOS_SOAK_FAIL: fleet learner died at startup"; exit 1; }
  sleep 0.2
done

python -m d4pg_tpu.fleet.actor --connect "127.0.0.1:$FLEET_PORT" \
  --bundle "$DIR/fleet_bundle" --batch-windows 8 --poll-interval 0.3 \
  --stats-interval 5 --seed 13 --reconnect-attempts 400 --debug-guards \
  --chaos "seed=7;reconnect_flap@1;stale_bundle@1;slow_link@3:150" \
  > "$DIR/fleet_actor.log" 2>&1 &
FACTOR=$!

# wait for the first committed checkpoint, then kill -9 the learner with
# the actor live — its link dies with frames in flight (dropped whole,
# never resent) and it must reconnect to the resumed run
FCKPT="$DIR/fleet/checkpoints"
for _ in $(seq 1 600); do
  compgen -G "$FCKPT/manifest_*.json" > /dev/null && break
  kill -0 "$FLEARNER" 2>/dev/null \
    || { cat "$DIR/fleet_learner1.log"; echo "CHAOS_SOAK_FAIL: fleet learner died before first commit"; exit 1; }
  sleep 0.5
done
sleep "0.$((RANDOM % 100))"
kill -9 "$FLEARNER" || true
wait "$FLEARNER" 2>/dev/null || true
echo "[chaos-soak] killed the fleet learner mid-ingest"

FNEWEST=$(ls "$FCKPT"/manifest_*.json | sed 's/.*manifest_\([0-9]*\).json/\1/' | sort -n | tail -1)
# metrics.jsonl is opened in APPEND mode across --resume: record where the
# first leg's rows end so the ingest assertion below can only be satisfied
# by rows the RESUMED learner wrote (else a pre-kill row passes it vacuously)
FPRE_ROWS=$(wc -l < "$DIR/fleet/metrics.jsonl" 2>/dev/null || echo 0)
python train.py "${fleet_learner[@]}" --resume \
  --total-steps $((FNEWEST + FLEET_STEPS)) --checkpoint-interval 100000 \
  --chaos "seed=5;partition@6" \
  > "$DIR/fleet_learner2.log" 2>&1 \
  || { cat "$DIR/fleet_learner2.log"; echo "CHAOS_SOAK_FAIL: resumed fleet learner exited non-zero"; exit 1; }
grep -q "\[checkpoint\] resumed from step" "$DIR/fleet_learner2.log" \
  || { cat "$DIR/fleet_learner2.log"; echo "CHAOS_SOAK_FAIL: fleet resume did not report its restored step"; exit 1; }
grep -q "\[lockwitness\].*0 contradictions" "$DIR/fleet_learner2.log" \
  || { cat "$DIR/fleet_learner2.log"; echo "CHAOS_SOAK_FAIL: resumed fleet learner recorded no lock-order witness verdict"; exit 1; }

kill -TERM "$FACTOR"
wait "$FACTOR" \
  || { cat "$DIR/fleet_actor.log"; echo "CHAOS_SOAK_FAIL: fleet actor drain exited non-zero"; exit 1; }

# every emitted window accounted (torn windows never half-land: they are
# either acked, counted stale/shed/dropped, or still spooled) — asserted
# by the actor's own --debug-guards ConservationLedger, whose
# [flow-verdict] line we parse instead of re-deriving the arithmetic in
# bash; plus the actor reconnected at least once (the kill -9 guarantees
# it), and the resumed learner ingested real windows with guards green.
python - "$DIR" "$FPRE_ROWS" <<'EOF'
import ast, json, sys
d, pre_rows = sys.argv[1], int(sys.argv[2])
verdicts = [json.loads(l.split("[flow-verdict]", 1)[1])
            for l in open(f"{d}/fleet_actor.log") if "[flow-verdict]" in l]
fam = [v for v in verdicts if v["family"] == "fleet-actor"]
assert fam, "actor drain emitted no fleet-actor flow verdict"
assert all(v["ok"] for v in fam), fam
drained = [l for l in open(f"{d}/fleet_actor.log") if "drained:" in l][-1]
s = ast.literal_eval(drained.split("drained:", 1)[1].strip())
assert s["reconnects"] >= 1, s
# only rows APPENDED by the resumed leg count — a surviving pre-kill row
# must not satisfy the ingest assertion vacuously
rows = [json.loads(l) for l in open(f"{d}/fleet/metrics.jsonl")][pre_rows:]
fleet = [r for r in rows if "fleet_windows_ingested" in r]
assert fleet, "resumed learner wrote no fleet metrics rows"
assert fleet[-1]["fleet_windows_ingested"] > 0, "resumed learner ingested nothing"
print("CHAOS_SOAK_FLEET_OK", {k: s[k] for k in
      ("windows_emitted", "windows_acked", "windows_dropped_reconnect",
       "reconnects", "bundle_reloads")})
EOF

# ---- leg 6: replicated serving fleet under replica kill + corrupt canary ---
# Sustained closed-loop load through the router front-end across TWO
# --debug-guards replicas while (a) one replica is SIGKILLed mid-stream,
# restarted, and re-admitted, and (b) a CORRUPT canary bundle is offered
# (router --chaos canary_corrupt truncates the deployed params) and must
# auto-roll-back with the baseline replica never reloading. Contracts:
# the accounting identity (every submitted request answered ok /
# OVERLOADED / error — zero silent losses), zero recompiles on surviving
# replicas (healthz compile_count flat + the sentinel's bucket budget
# asserted by each replica's rc-0 drain), and metrics rows attributable
# per replica (--replica-id).
cp -r "$DIR/bundle" "$DIR/r0"
cp -r "$DIR/bundle" "$DIR/r1"
python - "$DIR" <<'EOF'
import json, shutil, signal, sys, threading, time
import numpy as np

sys.path.insert(0, "scripts")
from spawnlib import spawn

d = sys.argv[1]


def replica(rid, port=0):
    return spawn(
        [sys.executable, "-m", "d4pg_tpu.serve",
         "--bundle", f"{d}/r{rid}", "--port", str(port),
         "--max-batch", "8", "--max-wait-us", "500",
         "--poll-interval", "0.2", "--replica-id", str(rid),
         "--debug-guards", "--log-dir", f"{d}/r{rid}_logs",
         "--metrics-interval", "2"],
        f"replica{rid}",
    )


reps = [replica(0), replica(1)]
ports = [r.wait_port(180) for r in reps]

router = spawn(
    [sys.executable, "-m", "d4pg_tpu.serve.router",
     "--backends", ",".join(f"127.0.0.1:{p}" for p in ports),
     "--backend-bundles", f"{d}/r0,{d}/r1",
     "--port", "0", "--probe-interval", "0.2", "--readmit-after", "2",
     "--canary-bundle", f"{d}/canary_src", "--canary-fraction", "0.5",
     "--canary-min-samples", "10", "--canary-attest-timeout", "30",
     "--debug-guards", "--chaos", "seed=11;canary_corrupt@1"],
    "router",
)
rport = router.wait_port(120)
for _ in range(300):
    if any("admitted 2/2" in l for l in router.lines):
        break
    time.sleep(0.2)
else:
    raise SystemExit("CHAOS_SOAK_FAIL: router never admitted both replicas")

from d4pg_tpu.serve.client import PolicyClient, Overloaded

obs = np.array([0.1, -0.2, 0.05], np.float32)
counts = {"ok": 0, "overloaded": 0, "error": 0}
lock = threading.Lock()
stop = threading.Event()


def load_loop():
    # one blocking chain: every act() resolves to exactly ONE outcome, so
    # the client-side tally IS the accounting identity's left side
    with PolicyClient("127.0.0.1", rport, timeout=60) as c:
        while not stop.is_set():
            try:
                a = c.act(obs, timeout=60)
                assert a.shape == (1,) and abs(float(a[0])) <= 2.0, a
                k = "ok"
            except Overloaded:
                k = "overloaded"
            except Exception:
                k = "error"
            with lock:
                counts[k] += 1


threads = [
    threading.Thread(target=load_loop, name=f"load{i}", daemon=True)
    for i in range(6)
]
for t in threads:
    t.start()


def healthz():
    from d4pg_tpu.serve.protocol import probe_healthz

    return probe_healthz("127.0.0.1", rport, timeout_s=5.0)


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise SystemExit(f"CHAOS_SOAK_FAIL: timed out waiting for {what}")


time.sleep(2)  # sustained load on the healthy fleet first

# ---- (a) SIGKILL replica 0 mid-stream, restart, re-admission ---------------
reps[0].proc.kill()
print("[chaos-soak] SIGKILLed replica 0 under load", flush=True)
wait_for(lambda: healthz()["admitted"] == 1, 60, "ejection of the dead replica")
reps[0] = replica(0, port=ports[0])  # same address, fresh process
reps[0].wait_port(180)
wait_for(lambda: healthz()["admitted"] == 2, 120, "re-admission after restart")
print("[chaos-soak] replica 0 restarted and re-admitted", flush=True)

# ---- (b) offer a canary the router's chaos plan corrupts -------------------
shutil.copytree(f"{d}/bundle", f"{d}/canary_src")
wait_for(
    lambda: healthz()["canary_rollbacks"] >= 1,
    120,
    "auto-rollback of the corrupt canary",
)
wait_for(
    lambda: (lambda h: h["canary"]["state"] == "idle" and h["admitted"] == 2)(
        healthz()
    ),
    120,
    "rollback settle + canary re-admission",
)
print("[chaos-soak] corrupt canary rolled back", flush=True)

time.sleep(2)  # load rides on the restored fleet
stop.set()
for t in threads:
    t.join(timeout=90)
    assert not t.is_alive(), "load thread wedged"

h = healthz()
submitted = sum(counts.values())
assert submitted > 0 and counts["ok"] > 0, counts
# identity (client side): every request answered ok / OVERLOADED / error
# (error = failed-after-bounded-retry; the threads count every outcome).
# The router-side identity (every admitted ACT answered) is asserted by
# the router's own --debug-guards ConservationLedger at drain — its
# [flow-verdict] lines are parsed after the stop() below.
assert h["canary_rollbacks"] == 1 and h["canary_promotions"] == 0, h
assert h["ejections"] >= 2 and h["admissions"] >= 4, h  # kill + rollback
# the corrupt deploy really fired and the rollback re-ejected the canary
assert any("canary_rollback" in l for l in router.lines)
# baseline (replica 0, restarted) NEVER reloaded; canary (replica 1)
# recovered onto the restored bundle with its compiled programs intact
from d4pg_tpu.serve.protocol import probe_healthz as probe

h0 = probe("127.0.0.1", ports[0], timeout_s=5.0)
h1 = probe("127.0.0.1", ports[1], timeout_s=5.0)
assert h0["params_reloads"] == 0, h0
assert h0["status"] == "ok" and h1["status"] == "ok", (h0, h1)
assert h0["compile_count"] == 4 and h1["compile_count"] == 4, (h0, h1)
assert h0["replica_id"] == 0 and h1["replica_id"] == 1

# ---- graceful drains: rc 0 = sentinel bucket budgets + guards clean --------
# (spawnlib.Spawned.stop is the one bounded SIGTERM->group-SIGKILL
# escalation — a drain-deaf process gets reaped instead of hanging the
# soak in proc.wait)
rc = router.stop(drain_timeout_s=120)
assert rc == 0, f"router exit {rc}"
# drain-time conservation verdicts: requests_total == ok + overloaded +
# error (aggregate) and gate evaluations == pass + block + stalls, from
# the ledger the router armed under --debug-guards
verdicts = [json.loads(l.split("[flow-verdict]", 1)[1])
            for l in router.lines if "[flow-verdict]" in l]
for fam in ("router", "router-gate", "router-tenant"):
    fv = [v for v in verdicts if v["family"] == fam]
    assert fv, f"router drain emitted no {fam} flow verdict"
    assert all(v["ok"] for v in fv), fv
for rid in (0, 1):
    rc = reps[rid].stop(drain_timeout_s=120)
    assert rc == 0, f"replica {rid} exit {rc} (guards/sentinel not clean?)"
    # each replica's serve drain balanced its admitted-request books
    rv = [json.loads(l.split("[flow-verdict]", 1)[1])
          for l in reps[rid].lines if "[flow-verdict]" in l]
    sv = [v for v in rv if v["family"] == "serve-stats"]
    assert sv and all(v["ok"] for v in sv), (rid, rv)

# metrics attribution: every surviving replica's rows carry ITS replica_id
for rid in (0, 1):
    rows = [json.loads(l) for l in open(f"{d}/r{rid}_logs/metrics.jsonl")]
    assert rows and all(r["replica_id"] == float(rid) for r in rows), rid

print("CHAOS_SOAK_ROUTER_OK",
      {"submitted": submitted, **counts,
       "retries": h["retries"], "ejections": h["ejections"],
       "admissions": h["admissions"],
       "rollbacks": h["canary_rollbacks"]})
EOF

# ---- leg 7: multi-tenant serving — tenant flood + policy skew + autoscaled
# scale-down mid-canary (ISSUE 12). Two multi-policy replicas (default +
# alt, each policy its own bundle dir), a router with per-tenant quotas +
# class-aware admission and the in-process autoscaler (min 2, max 3,
# spawning real serve CLIs via spawnlib). Contracts asserted below in the
# heredoc; SOAK_MT_SLO_MS bounds the interactive tier's p99.
for rep in 0 1; do
  cp -r "$DIR/bundle" "$DIR/mt_r${rep}_def"
  cp -r "$DIR/bundle" "$DIR/mt_r${rep}_alt"
done
python - "$DIR" "${SOAK_MT_SLO_MS:-2000}" <<'EOF'
import json, os, shutil, signal, sys, threading, time
import numpy as np

sys.path.insert(0, "scripts")
from spawnlib import spawn

d, slo_ms = sys.argv[1], float(sys.argv[2])


def replica(rid):
    return spawn(
        [sys.executable, "-m", "d4pg_tpu.serve",
         "--bundle", f"{d}/mt_r{rid}_def",
         "--policy", f"alt={d}/mt_r{rid}_alt",
         "--port", "0", "--max-batch", "8", "--max-wait-us", "500",
         "--poll-interval", "0.2", "--replica-id", str(rid),
         "--debug-guards"],
        f"mt-replica{rid}",
    )


reps = [replica(0), replica(1)]
ports = [r.wait_port(180) for r in reps]

router = spawn(
    [sys.executable, "-m", "d4pg_tpu.serve.router",
     "--backends", ",".join(f"127.0.0.1:{p}" for p in ports),
     "--backend-bundles",
     ",".join(f"default={d}/mt_r{r}_def+alt={d}/mt_r{r}_alt"
              for r in (0, 1)),
     "--port", "0", "--probe-interval", "0.2", "--readmit-after", "1",
     "--debug-guards",
     "--replica-capacity", "8", "--bulk-fraction", "0.5",
     "--tenant-quota", "bulky=40:60",
     "--canary-bundle", f"{d}/mt_canary",
     "--canary-fraction", "0.5", "--canary-min-samples", "8",
     "--canary-attest-timeout", "60", "--canary-observe-timeout", "30",
     "--autoscale", "--autoscale-min", "2", "--autoscale-max", "3",
     "--autoscale-bundle", f"{d}/bundle",
     "--autoscale-workdir", f"{d}/mt_autoscale",
     "--autoscale-interval", "0.4", "--autoscale-samples", "2",
     "--autoscale-cooldown", "2", "--autoscale-up-load", "0.7",
     "--replica-args", "--max-batch 8 --max-wait-us 500",
     "--flood-burst", "150",
     "--chaos",
     "seed=17;tenant_flood@60:bulky;policy_skew@120;"
     "scaledown_during_canary@28"],
    "mt-router",
)
rport = router.wait_port(120)
for _ in range(300):
    if any("admitted 2/2" in l for l in router.lines):
        break
    time.sleep(0.2)
else:
    raise SystemExit("CHAOS_SOAK_FAIL: mt router never admitted both replicas")

from d4pg_tpu.serve.client import PolicyClient
from d4pg_tpu.serve.protocol import probe_healthz

obs = np.array([0.1, -0.2, 0.05], np.float32)
stop = threading.Event()
lock = threading.Lock()
tallies = {}   # (label) -> {"ok": n, "overloaded": n, "error": n}
idle = threading.Semaphore(0)
CHAINS = []


def chain(label, client, window, **act_kw):
    """Closed-loop async chain: every completion immediately re-sends —
    the client-side half of the accounting identity."""
    counts = tallies.setdefault(label, {"ok": 0, "overloaded": 0, "error": 0})

    def send():
        fut = client.act_async(obs, **act_kw)

        def done(f):
            exc = f.exception()
            with lock:
                if exc is None:
                    counts["ok"] += 1
                elif type(exc).__name__ == "Overloaded":
                    counts["overloaded"] += 1
                else:
                    counts["error"] += 1
            if stop.is_set():
                idle.release()
            else:
                send()

        fut.add_done_callback(done)

    for _ in range(window):
        send()
    CHAINS.append(window)


clients = []


def mk_client(**kw):
    c = PolicyClient("127.0.0.1", rport, timeout=60, **kw)
    clients.append(c)
    return c


# interactive tenants (the protected tier), a bulk flooder, and the cold
# alt policy under a per-request deadline
for i in range(3):
    chain(f"web{i}", mk_client(tenant="web"), 6)
chain("bulky", mk_client(tenant="bulky", qos="bulk"), 10)
chain("alt", mk_client(tenant="web", policy_id="alt"), 2,
      deadline_ms=slo_ms)


def healthz():
    return probe_healthz("127.0.0.1", rport, timeout_s=5.0)


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except OSError:
            pass
        time.sleep(0.3)
    raise SystemExit(f"CHAOS_SOAK_FAIL: timed out waiting for {what}")


# (a) the load pushes utilization over the line: the autoscaler grows the
# fleet to 3 (a REAL spawned serve CLI admitted through the probe path)
wait_for(lambda: healthz()["admitted"] == 3, 120, "autoscaler scale-up")
print("[chaos-soak] autoscaler scaled 2 -> 3 under load", flush=True)

# (b) the tenant flood + policy skew chaos bursts fire on request counts
wait_for(lambda: healthz().get("chaos_injections", 0) >= 2, 60,
         "tenant_flood + policy_skew injections")

# (c) offer a canary for the DEFAULT policy (same params re-attested =
# a new version), then the chaos-forced scale-down lands mid-rollout
shutil.copytree(f"{d}/bundle", f"{d}/mt_canary")
wait_for(
    lambda: any("scale_down" in l and "scaledown_skipped" not in l
                for l in router.lines),
    120, "chaos-forced scale-down",
)
print("[chaos-soak] chaos forced a scale-down", flush=True)
wait_for(
    lambda: (lambda h: all(
        ro["state"] == "idle" for ro in h["rollouts"].values()
    ) and h["admitted"] >= 2)(healthz()),
    180, "rollout settle after scale-down",
)
print("[chaos-soak] rollout settled cleanly after scale-down", flush=True)

time.sleep(2)  # load rides on the settled fleet
stop.set()
for _ in range(sum(CHAINS)):
    idle.acquire(timeout=90)
for c in clients:
    c.close()

h = healthz()
# The aggregate + per-(tenant, class) accounting identities are asserted
# EXACTLY at drain by the router's --debug-guards ConservationLedger
# ([flow-verdict] lines parsed after stop() below) — healthz keeps the
# load-shape asserts that need a live snapshot.
# the flood was real and bulk shed FIRST: the bulk tenant absorbed
# overload at its quota/bulk-capacity lines...
bulk = h["tenants"]["bulky/bulk"]
assert bulk["overloaded"] > 0, bulk
assert h["shed_quota"] + h["shed_bulk_capacity"] > 0, h
# ...while the interactive tier's p99 stayed inside its SLO
p99 = h["interactive"]["p99_ms"]
assert p99 is not None and p99 <= slo_ms, (p99, slo_ms)
# the cold policy under skew still answered inside its deadline: no
# errors, sheds bounded, real successes
alt = h["tenants"]["web/interactive"]
with lock:
    alt_counts = dict(tallies["alt"])
assert alt_counts["error"] == 0, alt_counts
assert alt_counts["ok"] >= 20, alt_counts
assert alt_counts["overloaded"] <= alt_counts["ok"], alt_counts
# client-side totals reconcile with the router's answered counts
with lock:
    client_total = sum(sum(t.values()) for t in tallies.values())
# (synthetic chaos bursts are router-side extras: answered >= client)
assert h["answered_total"] >= client_total, (h["answered_total"], client_total)
# scale-down mid-canary stranded NOTHING: every live replica attests the
# bundle its dirs carry, and every bundle dir on disk (seed fleet,
# autoscaler spawns, canary source) is params+json CONSISTENT
for rep_row in h["replicas"]:
    if rep_row["removed"]:
        continue
    for pol, mt in rep_row["policy_mtimes"].items():
        assert mt is not None, rep_row
import glob
from d4pg_tpu.serve.bundle import load_bundle
for bdir in ([f"{d}/mt_r{r}_{p}" for r in (0, 1) for p in ("def", "alt")]
             + sorted(glob.glob(f"{d}/mt_autoscale/autoscale_r*"))):
    load_bundle(bdir)  # raises on a half-deployed params/json mixture
# no OTHER policy was touched by the default-policy rollout
for p in ports:
    rows = probe_healthz("127.0.0.1", p, timeout_s=5.0)["policies"]
    assert rows["alt"]["params_reloads"] == 0, rows

# graceful drains: rc 0 = sentinel per-policy bucket budgets + guards clean
# (the shared bounded escalation — see leg 6)
rc = router.stop(drain_timeout_s=180)
assert rc == 0, f"mt router exit {rc}"
# drain-time conservation verdicts: the aggregate request identity, the
# gate identity, and EVERY per-(tenant, class) row (bad_rows == 0)
verdicts = [json.loads(l.split("[flow-verdict]", 1)[1])
            for l in router.lines if "[flow-verdict]" in l]
for fam in ("router", "router-gate", "router-tenant"):
    fv = [v for v in verdicts if v["family"] == fam]
    assert fv, f"mt router drain emitted no {fam} flow verdict"
    assert all(v["ok"] for v in fv), fv
tenant_rows = [v for v in verdicts if v["family"] == "router-tenant"][-1]
assert tenant_rows["counters"]["rows"] >= 3, tenant_rows  # flood was real
for rid in (0, 1):
    rc = reps[rid].stop(drain_timeout_s=120)
    assert rc == 0, f"mt replica {rid} exit {rc} (guards/sentinel not clean?)"
    rv = [json.loads(l.split("[flow-verdict]", 1)[1])
          for l in reps[rid].lines if "[flow-verdict]" in l]
    sv = [v for v in rv if v["family"] == "serve-stats"]
    assert sv and all(v["ok"] for v in sv), (rid, rv)

print("CHAOS_SOAK_MT_OK", json.dumps({
    "interactive_p99_ms": p99, "slo_ms": slo_ms,
    "requests_total": h["requests_total"],
    "shed_quota": h["shed_quota"],
    "shed_bulk_capacity": h["shed_bulk_capacity"],
    "ejections": h["ejections"], "admissions": h["admissions"],
    "canary_rollbacks": h["canary_rollbacks"],
    "canary_promotions": h["canary_promotions"],
    "tenants": {k: v["requests"] for k, v in h["tenants"].items()},
}))
EOF

# ---- leg 8: one data plane — fleet-fed HER + obs-norm + pixel wire under ---
# ---- stale_stats / pixel_truncate / her_actor_kill (ISSUE 13) --------------
# A fleet-ONLY learner on a goal env with --her --obs-norm (the cells the
# old refusal matrix closed), fed by a REAL actor host doing actor-side
# hindsight relabeling with generation-tagged obs-norm stats riding the
# bundle, WINDOWS2 frames on the wire. Chaos: the actor keeps stale stats
# across a hot-swap (ingest must age those windows out with an honest
# count), truncates a frame mid-send (torn frame whole-drops), and
# SIGKILLs itself mid-episode (the buffered HER episode dies with it; a
# supervisor restart reconnects). Contracts: learner rc 0 with guards
# green, the restarted actor's at-most-once accounting identity EXACT,
# and the stale-stats drop actually observed.
FLEET8_PORT=$((20000 + RANDOM % 20000))
FLEET8_STEPS=${SOAK_FLEET8_STEPS:-200}
export PYTHONPATH="$PWD/tests${PYTHONPATH:+:$PYTHONPATH}"  # ToyGoal-v0
# env-steps-per-train-step 30 stretches the learner across many actor
# episodes so the actor-1 death, the restart, AND actor-2's stale-stats
# swap all land while it still ingests (windows ARE env steps here)
leg8_learner=(--env "toy_goal_env:ToyGoal-v0" --hidden-sizes "$HIDDEN"
              --her --her-k 2 --obs-norm --n-step 3
              --warmup 24 --bsize 8 --rmsize 2048 --eval-interval 100000
              --num-envs 0 --fleet-listen "$FLEET8_PORT"
              --fleet-bundle "$DIR/fleet8_bundle"
              --fleet-publish-interval 3 --fleet-max-gen-lag 1
              --env-steps-per-train-step 30
              --debug-guards --no-concurrent-eval
              --log-dir "$DIR/fleet8")

python train.py "${leg8_learner[@]}" --total-steps "$FLEET8_STEPS" \
  --checkpoint-interval 100000 \
  > "$DIR/fleet8_learner.log" 2>&1 &
F8LEARNER=$!
for _ in $(seq 1 600); do
  [ -f "$DIR/fleet8_bundle/bundle.json" ] \
    && grep -q "ingest listening" "$DIR/fleet8_learner.log" && break
  kill -0 "$F8LEARNER" 2>/dev/null \
    || { cat "$DIR/fleet8_learner.log"; echo "CHAOS_SOAK_FAIL: leg8 learner died at startup"; exit 1; }
  sleep 0.2
done

# actor 1: truncates its 2nd frame mid-send, then SIGKILLs itself
# mid-episode (env step 60 ≈ its 3rd ToyGoal episode)
python -m d4pg_tpu.fleet.actor --connect "127.0.0.1:$FLEET8_PORT" \
  --bundle "$DIR/fleet8_bundle" --env "toy_goal_env:ToyGoal-v0" \
  --her --her-k 2 --batch-windows 8 --poll-interval 0.2 \
  --stats-interval 5 --seed 21 --reconnect-attempts 400 \
  --chaos "seed=9;pixel_truncate@2;her_actor_kill@60" \
  > "$DIR/fleet8_actor1.log" 2>&1 &
F8A1=$!
# wait for the SIGKILL chaos to fire (the supervisor-restart story)
for _ in $(seq 1 600); do
  kill -0 "$F8A1" 2>/dev/null || break
  sleep 0.2
done
kill -0 "$F8A1" 2>/dev/null \
  && { echo "CHAOS_SOAK_FAIL: her_actor_kill never fired"; exit 1; }
grep -q "her_actor_kill: SIGKILL self" "$DIR/fleet8_actor1.log" \
  || { cat "$DIR/fleet8_actor1.log"; echo "CHAOS_SOAK_FAIL: actor died for the wrong reason"; exit 1; }

# actor 2: the restart — its FIRST bundle hot-swap keeps the old stats
# (stale_stats@1); publishes outpace the 0.3 s poll by design, so the
# pinned stats generation falls > max-gen-lag behind mid-run and the
# ingest drop path is observed while the learner still logs
python -m d4pg_tpu.fleet.actor --connect "127.0.0.1:$FLEET8_PORT" \
  --bundle "$DIR/fleet8_bundle" --env "toy_goal_env:ToyGoal-v0" \
  --her --her-k 2 --batch-windows 8 --poll-interval 0.3 \
  --stats-interval 5 --seed 22 --reconnect-attempts 400 \
  --chaos "seed=11;stale_stats@1" \
  > "$DIR/fleet8_actor2.log" 2>&1 &
F8A2=$!

wait "$F8LEARNER" \
  || { cat "$DIR/fleet8_learner.log"; echo "CHAOS_SOAK_FAIL: leg8 learner exited non-zero"; exit 1; }
grep -q "\[lockwitness\].*0 contradictions" "$DIR/fleet8_learner.log" \
  || { cat "$DIR/fleet8_learner.log"; echo "CHAOS_SOAK_FAIL: leg8 learner recorded no lock-order witness verdict"; exit 1; }

kill -TERM "$F8A2" 2>/dev/null || true
wait "$F8A2" \
  || { cat "$DIR/fleet8_actor2.log"; echo "CHAOS_SOAK_FAIL: leg8 actor-2 drain exited non-zero"; exit 1; }

python - "$DIR" <<'EOF'
import ast, json, sys
d = sys.argv[1]
# the restarted actor's at-most-once identity is EXACT
drained = [l for l in open(f"{d}/fleet8_actor2.log") if "drained:" in l][-1]
s = ast.literal_eval(drained.split("drained:", 1)[1].strip())
acct = (s["windows_acked"] + s["windows_stale"] + s["windows_shed"]
        + s["windows_dropped_reconnect"] + s["windows_dropped_spool"]
        + s["spool_depth"])
assert acct == s["windows_emitted"], (acct, s)
# the learner ingested relabeled + original windows with guards green
# (rc 0 above) and observed the chaos: stale-stats drops counted, the
# truncated frame died as a protocol error, never a torn window
rows = [json.loads(l) for l in open(f"{d}/fleet8/metrics.jsonl")]
fleet = [r for r in rows if "fleet_windows_ingested" in r]
assert fleet and fleet[-1]["fleet_windows_ingested"] > 0
last = fleet[-1]
a2 = open(f"{d}/fleet8_actor2.log").read()
assert "chaos stale_stats" in a2, "stale_stats never fired"
assert last.get("fleet_windows_dropped_stale_stats", 0) > 0, last
assert last.get("fleet_protocol_errors", 0) >= 1, last  # truncated frame
assert last.get("fleet_handshake_refusals", 0) == 0, last
print("CHAOS_SOAK_LEG8_OK", {
    "ingested": last["fleet_windows_ingested"],
    "dropped_stale_stats": last["fleet_windows_dropped_stale_stats"],
    "protocol_errors": last["fleet_protocol_errors"],
    "actor2": {k: s[k] for k in ("windows_emitted", "windows_acked",
                                 "windows_dropped_reconnect")},
})
EOF

# ---- leg 9: league training — PBT over REAL learners under chaos (ISSUE 15)
# A seeded 3-variant league of real train.py learners (--debug-guards,
# fleet-only: each variant its own ingest port + one actor host pinned to
# its variant id through the HELLO capability vector). Fitness separation
# is baked into the genomes (the 50-step-horizon variant deterministically
# out-scores the 200-step ones on Pendulum's all-negative rewards).
# Chaos: variant_kill (a learner's whole process group SIGKILLed —
# supervisor restart under seeded Backoff, its actor reconnects),
# clone_corrupt (the newest FORKED checkpoint step truncated — the
# clone's verify-on-restore must fall back to the older copied step),
# and a controller kill -9 MID-GENERATION (event-triggered from here the
# moment the journal holds pending work — deterministic by construction).
# Contracts: the rerun resumes the SAME generation (never double-books),
# re-adopts/restarts learners, promotes the planted winner's bloodline,
# every drained learner's lockwitness records 0 contradictions, the
# per-variant process-tenure accounting identity is EXACT (schema-gated
# summary artifact), every actor's at-most-once window identity is
# EXACT, and zero learner/actor processes survive the league.
LEAGUE9_PORT=$((23000 + RANDOM % 10000))
league9_args=(--seed 7 --generations 1 --poll-interval 0.3
              --gen-timeout 300 --drain-timeout 90
              --attest-timeout 240 --observe-timeout 300
              --fleet-base-port "$LEAGUE9_PORT" --actors-per-variant 1
              --actor-args "--batch-windows 8 --poll-interval 0.3 --stats-interval 10"
              --genome 'lr_actor=1e-4,max_episode_steps=50'
              --genome 'lr_actor=1e-4,max_episode_steps=200'
              --genome 'lr_actor=3e-3,max_episode_steps=200')
league9_learner=(python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN"
                 --warmup 24 --bsize 8 --rmsize 512
                 --eval-interval 2 --eval-episodes 1
                 --checkpoint-interval 4 --total-steps 100000
                 --snapshot-replay --debug-guards)

python -m d4pg_tpu.league --dir "$DIR/league" "${league9_args[@]}" \
  --chaos "seed=5;variant_kill@40;clone_corrupt@1" \
  -- "${league9_learner[@]}" > "$DIR/league9_run1.log" 2>&1 &
L9CTL=$!
# kill -9 the controller the moment a generation is IN FLIGHT (pending
# work journaled): mid-generation by construction, not by tick roulette
for _ in $(seq 1 3000); do
  PENDING=$(python -c "
import json,sys
try: d=json.load(open('$DIR/league/league.json'))
except Exception: sys.exit(0)
print('yes' if d.get('pending') else '')" 2>/dev/null || true)
  [ "$PENDING" = "yes" ] && break
  kill -0 "$L9CTL" 2>/dev/null || { cat "$DIR/league9_run1.log"; echo "CHAOS_SOAK_FAIL: league controller died before planning a generation"; exit 1; }
  sleep 0.2
done
[ "$PENDING" = "yes" ] || { cat "$DIR/league9_run1.log"; echo "CHAOS_SOAK_FAIL: no pending generation within the deadline"; exit 1; }
sleep "0.$((RANDOM % 100))"   # a random instant INSIDE the apply window
kill -9 "$L9CTL" || true
wait "$L9CTL" 2>/dev/null || true
GEN9=$(python -c "import json;print(json.load(open('$DIR/league/league.json'))['generation'])")
echo "[chaos-soak] killed the league controller mid-generation (gen $GEN9)"

# the rerun: same args (journal-checked), clone_corrupt re-armed so the
# fork fires truncated whichever side of the crash it lands on
python -m d4pg_tpu.league --dir "$DIR/league" "${league9_args[@]}" \
  --chaos "seed=5;clone_corrupt@1" --debug-guards \
  --summary-out "$DIR/league_soak.json" \
  -- "${league9_learner[@]}" > "$DIR/league9_run2.log" 2>&1 \
  || { tail -80 "$DIR/league9_run2.log"; echo "CHAOS_SOAK_FAIL: league rerun exited non-zero"; exit 1; }
grep -q "journal_resumed" "$DIR/league9_run2.log" \
  || { echo "CHAOS_SOAK_FAIL: league rerun did not resume the journal"; exit 1; }
grep -hq "chaos.*variant_kill: SIGKILL" "$DIR/league9_run1.log" "$DIR/league9_run2.log" \
  || { echo "CHAOS_SOAK_FAIL: variant_kill never fired"; exit 1; }
grep -hq "truncated" "$DIR/league9_run1.log" "$DIR/league9_run2.log" \
  || { echo "CHAOS_SOAK_FAIL: clone_corrupt never truncated a fork"; exit 1; }

python - "$DIR" "$GEN9" <<'EOF'
import ast, json, sys
d, gen_at_crash = sys.argv[1], int(sys.argv[2])
logs = open(f"{d}/league9_run1.log").read() + open(f"{d}/league9_run2.log").read()
s = json.load(open(f"{d}/league_soak.json"))
# the SAME generation the crash interrupted resumed and committed ONCE
events = [json.loads(l) for l in open(f"{d}/league/league_events.jsonl")]
done = [e["gen"] for e in events if e["event"] == "generation_done"]
assert sorted(set(done)) == done, f"a generation committed twice: {done}"
assert s["generations_completed"] == 1 and gen_at_crash == 0, (s, gen_at_crash)
# the planted winner's bloodline won: every fork descends from uid 1
def root(uid, variants):
    while variants[str(uid)]["parent"] is not None:
        uid = variants[str(uid)]["parent"]
    return uid
assert s["promotions"] >= 1, s
assert s["lineage"] and all(
    root(e["parent"], s["variants"]) == 1 for e in s["lineage"]
), s["lineage"]
# the torn fork was never trained on: the clone's verified restore
# either fell back (fallback logged) or the fork pre-dated the crash
assert "[checkpoint]" in logs
# per-variant process-tenure accounting identity, via the schema gate
sys.path.insert(0, ".")
from tools.d4pglint.schema_check import check_league_soak
errs = check_league_soak(f"{d}/league_soak.json")
assert not errs, errs
assert s["identity_ok"] is True and s["orphans_swept"] == 0, s
# ...and via the rerun's --debug-guards ConservationLedger: the same
# tenure equation per variant row, machine-checked at summary time
ltv = [json.loads(l.split("[flow-verdict]", 1)[1])
       for l in open(f"{d}/league9_run2.log") if "[flow-verdict]" in l]
ltv = [v for v in ltv if v["family"] == "league-tenure"]
assert ltv and all(v["ok"] for v in ltv), ltv
assert ltv[-1]["counters"]["bad_rows"] == 0, ltv
# every drained learner's lock-order witness: 0 contradictions, and the
# guards never tripped (non-zero learner exits other than kill/preempt
# would have broken the identity above)
assert logs.count("0 contradictions") >= 2, logs.count("0 contradictions")
# every fleet actor's at-most-once accounting identity is EXACT
drains = [l for l in logs.splitlines() if "drained:" in l]
assert drains, "no actor drain accounting lines"
for line in drains:
    st = ast.literal_eval(line.split("drained:", 1)[1].strip())
    acct = (st["windows_acked"] + st["windows_stale"] + st["windows_shed"]
            + st["windows_dropped_reconnect"] + st["windows_dropped_spool"]
            + st["spool_depth"])
    assert acct == st["windows_emitted"], (acct, st)
print("CHAOS_SOAK_LEAGUE_OK", {
    "generations": s["generations_completed"],
    "promotions": s["promotions"], "rollbacks": s["rollbacks"],
    "restarts": sum(v["restarts"] for v in s["variants"].values()),
    "chaos_injections": s["chaos_injections"],
    "actors_drained": len(drains),
})
EOF

# zero league processes survive (learners AND actor hosts)
if pgrep -f "log-dir $DIR/league/v" > /dev/null 2>&1 \
   || pgrep -f "d4pg_tpu.fleet.actor.*$LEAGUE9_PORT" > /dev/null 2>&1; then
  echo "CHAOS_SOAK_FAIL: league processes survived the shutdown"
  pgrep -af "$DIR/league" || true
  exit 1
fi

# ---- leg 10: the flywheel — served traffic becomes training data, gated
# promotions close the loop (ISSUE 18). A fleet-only learner is paced
# ENTIRELY by the router's mirror tap (no actors, no local envs): two
# replicas serve the learner's random gen-0 bundle to noisy sim clients
# through the router, whose tap streams every served episode back to the
# learner's ingest and spools it for the gate. A promotion ladder then
# offers the learner's published generations a few hops at a time — each
# offer must clear the off-policy IS gate's verdict over the spooled
# windows, and each promotion moves the SERVING behavior, which is what
# keeps the next candidate inside the gate's effective-sample-size reach.
# Chaos: gate_stall (the first evaluation wedges — the observe deadline
# must bound it into a rollback, never a hang) and mirror_drop (tap
# losses stay on the books). A planted collapsed-constant bundle (serves
# error-free, steers the plant into the ground) must be BLOCKED by the
# gate before live error rate ever sees it. Contracts: the fixed-seed
# served return strictly rises across the soak (the bundle improved on
# its OWN served traffic), gate accounting exact (evaluations == pass +
# block + stalls), both planes' window identities exact, every drain
# rc 0, zero surviving processes, and the run emits the schema-gated
# flywheel_soak.json acceptance artifact.
python - "$DIR" <<'EOF'
import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np

sys.path.insert(0, "scripts")
sys.path.insert(0, ".")
from spawnlib import spawn

d = sys.argv[1]
F = f"{d}/flywheel"
os.makedirs(F, exist_ok=True)


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except OSError:
            pass
        time.sleep(0.3)
    raise SystemExit(f"CHAOS_SOAK_FAIL: timed out waiting for {what}")


# The learner: NO local collection, NO fleet actors — its pacing loop can
# only advance on windows the router's mirror tap feeds it. max-gen-lag is
# effectively off because flywheel data is off-generation BY DESIGN: the
# serving fleet always lags training, that is what the gate is for.
learner = spawn(
    [sys.executable, "train.py", "--env", "Pendulum-v1",
     "--hidden-sizes", "64,64", "--n-step", "3", "--tau", "0.005",
     "--lr-actor", "5e-4", "--lr-critic", "5e-4", "--bsize", "128",
     "--rmsize", "50000", "--warmup", "1500",
     "--env-steps-per-train-step", "2.0", "--total-steps", "4000",
     "--seed", "0", "--eval-interval", "1000000", "--eval-episodes", "1",
     "--checkpoint-interval", "1000000", "--num-envs", "0",
     "--fleet-listen", "0", "--fleet-host", "127.0.0.1",
     "--fleet-bundle", f"{F}/lbundle", "--fleet-publish-interval", "250",
     "--fleet-max-gen-lag", "1000000",
     "--debug-guards", "--log-dir", F],
    "fly-learner")
iport = learner.wait_port(600)
wait_for(lambda: os.path.exists(f"{F}/lbundle/bundle.json"), 300,
         "the learner's gen-0 publish")


def lgen():
    with open(f"{F}/lbundle/bundle.json") as f:
        return int(json.load(f)["meta"]["generation"])


def snapshot(dst):
    """Copy the learner's live publish dir without tearing a params/json
    pair (the export is params-first/json-second: an equal generation
    before and after the copy means the pair is consistent)."""
    for _ in range(50):
        g0 = lgen()
        if os.path.exists(dst):
            shutil.rmtree(dst)
        shutil.copytree(f"{F}/lbundle", dst)
        if lgen() == g0:
            return g0
        time.sleep(0.1)
    raise SystemExit("CHAOS_SOAK_FAIL: could not snapshot a stable bundle")


# gen 0 — the random init, the deliberately-degraded start — serves the fleet
for rid in (0, 1):
    snapshot(f"{F}/r{rid}")
reps = [
    spawn([sys.executable, "-m", "d4pg_tpu.serve",
           "--bundle", f"{F}/r{rid}", "--port", "0",
           "--max-batch", "8", "--max-wait-us", "500",
           "--poll-interval", "0.2", "--replica-id", str(rid),
           "--debug-guards"], f"fly-replica{rid}")
    for rid in (0, 1)
]
ports = [r.wait_port(300) for r in reps]

router = spawn(
    [sys.executable, "-m", "d4pg_tpu.serve.router",
     "--backends", ",".join(f"127.0.0.1:{p}" for p in ports),
     "--backend-bundles", f"{F}/r0,{F}/r1",
     "--port", "0", "--probe-interval", "0.2", "--readmit-after", "1",
     "--canary-bundle", f"{F}/canary_src",
     "--canary-fraction", "0.5", "--canary-min-samples", "20",
     "--canary-attest-timeout", "90", "--canary-observe-timeout", "30",
     "--mirror-fraction", "1.0",
     "--mirror-ingest", f"127.0.0.1:{iport}",
     "--mirror-spool", f"{F}/spool",
     "--gate-sigma", "0.3", "--gate-min-windows", "64",
     "--gate-min-ess", "16", "--gate-band", "3.0",
     "--gate-max-windows", "512",
     "--debug-guards",
     "--chaos", "seed=18;gate_stall@1:600;mirror_drop@400;mirror_drop@900",
     "--log-dir", F],
    "fly-router")
rport = router.wait_port(120)
wait_for(lambda: any("admitted 2/2" in l for l in router.lines), 180,
         "flywheel router admission")

from d4pg_tpu.serve.protocol import probe_healthz


def healthz():
    return probe_healthz("127.0.0.1", rport, timeout_s=5.0)


def evaluate(tag):
    """Fixed-seed serving quality through the ROUTER: plain v1 ACT
    traffic (σ=0, no feedback, nothing mirrored)."""
    p = subprocess.run(
        [sys.executable, "-m", "d4pg_tpu.flywheel.sim_client",
         "--connect", f"127.0.0.1:{rport}", "--env", "Pendulum-v1",
         "--episodes", "3", "--seed", "12345", "--noise-sigma", "0",
         "--no-feedback", "--max-steps", "200", "--retries", "64"],
        capture_output=True, text=True, timeout=600)
    out = p.stdout + p.stderr
    assert p.returncode == 0 and "SIM_CLIENT_OK" in p.stdout, (
        tag, out[-2000:])
    row = [l for l in p.stdout.splitlines() if "mean_return=" in l][-1]
    return float(row.split("mean_return=")[1].split()[0])


eval_before = evaluate("before")
print(f"[chaos-soak] flywheel eval BEFORE (gen 0): {eval_before:.1f}",
      flush=True)

# noisy served traffic — THE data source (σ must match --gate-sigma: the
# logged propensity is what the gate importance-weights by)
sims = [
    spawn([sys.executable, "-m", "d4pg_tpu.flywheel.sim_client",
           "--connect", f"127.0.0.1:{rport}", "--env", "Pendulum-v1",
           "--episodes", "1000000", "--seed", str(100 + i),
           "--noise-sigma", "0.3", "--max-steps", "100",
           "--retries", "64"], f"fly-sim{i}")
    for i in range(2)
]
wait_for(lambda: healthz().get("mirror", {}).get("windows_acked", 0) > 200,
         300, "mirrored windows reaching the learner")


def events(kind):
    rows = []
    for l in list(router.lines):
        if "[router-event]" not in l:
            continue
        try:
            e = json.loads(l.split("[router-event]", 1)[1])
        except ValueError:
            continue
        if e.get("event") == kind:
            rows.append(e)
    return rows


def offer(src):
    if os.path.exists(f"{F}/canary_src"):
        shutil.rmtree(f"{F}/canary_src")
    shutil.copytree(src, f"{F}/canary_src")
    # copytree preserves mtimes; a rollout only starts on a NEW mtime
    os.utime(f"{F}/canary_src/bundle.json", None)


def rollout_idle():
    ros = healthz().get("rollouts", {})
    return all(ro["state"] == "idle" for ro in ros.values())


# -- offer 1: chaos wedges the FIRST gate evaluation (gate_stall@1:600) —
# the observe deadline must bound it into a rollback, never a hang
snapshot(f"{F}/offer_stall")
offer(f"{F}/offer_stall")
wait_for(lambda: healthz()["canary_rollbacks"] >= 1, 300,
         "the stalled gate's bounded rollback")
stall_ev = events("canary_rollback")[0]
assert "stalled" in stall_ev["reason"], stall_ev
wait_for(rollout_idle, 180, "fleet settle after the stall rollback")
print("[chaos-soak] stalled gate evaluation rolled back (bounded)",
      flush=True)

# -- offer 2: the planted bad bundle — a collapsed constant policy that
# SERVES error-free (the live canary verdict sees nothing wrong) while
# steering the plant into the ground; only the off-policy gate sees it
snapshot(f"{F}/bad_bundle")
z = dict(np.load(f"{F}/bad_bundle/actor_params.npz"))
bias = min((k for k in z if z[k].ndim == 1), key=lambda k: z[k].size)
# Saturate toward the boundary the LOGGED traffic avoids: a constant on
# the behavior's own favored side would overlap the clip atoms there and
# score full ESS (indistinguishable from behavior — and as harmless).
# The side the serving distribution never visits is the one that IS the
# bad bundle: concentrated overlap on a handful of windows, ESS ~1.
# Pick that side with the GATE'S OWN estimator over the spool (the sign
# of the logged action mean is a bad proxy when the behavior straddles
# zero: both boundaries carry clip atoms and the mean says nothing about
# which side's atoms are thinner).
from d4pg_tpu.flywheel.spool import read_windows
from d4pg_tpu.flywheel.gate import CLIP_LOG_RHO, gaussian_log_prob

scols, sn = read_windows(f"{F}/spool", 3, 1, max_windows=512)
acts = np.asarray(scols["action"], np.float64)
logp = np.asarray(scols["logprob"], np.float64)


def plant_ess(boundary):
    lr = np.minimum(
        gaussian_log_prob(acts, np.full_like(acts, boundary), 0.3) - logp,
        CLIP_LOG_RHO)
    rho = np.exp(lr)
    s = float(rho.sum())
    return 0.0 if s <= 0.0 else s * s / float((rho * rho).sum())


ess_by_side = {b: plant_ess(b) for b in (-1.0, 1.0)}
side = 50.0 * min(ess_by_side, key=ess_by_side.get)
z[bias] = np.full_like(z[bias], side)  # tanh saturates: action ≡ ∓1
np.savez(f"{F}/bad_bundle/actor_params.npz", **z)
print(f"[chaos-soak] planting constant action {np.sign(side):+.0f} "
      f"(plant ESS by side {ess_by_side}, logged action mean "
      f"{float(np.mean(scols['action'])):+.3f} over {sn} spooled windows)",
      flush=True)
offer(f"{F}/bad_bundle")
wait_for(lambda: healthz()["canary_rollbacks"] >= 2, 300,
         "the gate blocking the planted bad bundle")
bad_ev = [e for e in events("canary_rollback")
          if e["reason"].startswith("off-policy gate:")][0]
bad_verdict = bad_ev["gate"]
assert bad_verdict["passed"] is False, bad_verdict
# blocked BEFORE the live plane saw anything: error rates were clean
assert (bad_ev["canary_error_rate"]
        <= bad_ev["baseline_error_rate"] + 0.05), bad_ev
wait_for(rollout_idle, 180, "fleet settle after the gate block")
print(f"[chaos-soak] bad bundle BLOCKED by the gate: "
      f"{bad_verdict['reason']}", flush=True)

# -- the promotion ladder: archive every published generation, then walk
# the serving fleet up the ladder a few generations per offer
archive = {}
arch_lock = threading.Lock()
arch_stop = threading.Event()


def archiver():
    while not arch_stop.is_set():
        try:
            g = lgen()
            with arch_lock:
                have = g in archive
            if not have:
                got = snapshot(f"{F}/gens/{g}.tmp")
                dst = f"{F}/gens/{got}"
                if os.path.exists(dst):
                    shutil.rmtree(f"{F}/gens/{g}.tmp")
                else:
                    os.rename(f"{F}/gens/{g}.tmp", dst)
                with arch_lock:
                    archive[got] = dst
        except (OSError, ValueError, SystemExit):
            pass
        time.sleep(0.3)


os.makedirs(f"{F}/gens", exist_ok=True)
threading.Thread(target=archiver, name="fly-archiver", daemon=True).start()

served_gen, hop = 0, 3
promoted_gens = []
final_gen = None
deadline = time.monotonic() + 1800
while True:
    if time.monotonic() > deadline:
        raise SystemExit("CHAOS_SOAK_FAIL: promotion ladder never converged")
    if final_gen is None and learner.proc.poll() is not None:
        rc = learner.proc.wait()
        assert rc == 0, f"flywheel learner exit {rc} (guards tripped?)"
        final_gen = lgen()
        print(f"[chaos-soak] flywheel learner done rc 0 "
              f"(final gen {final_gen})", flush=True)
    if not rollout_idle():
        time.sleep(0.5)
        continue
    with arch_lock:
        gens = sorted(archive)
    ahead = [g for g in gens if g > served_gen]
    if not ahead:
        if final_gen is not None and served_gen >= final_gen:
            break
        time.sleep(0.5)
        continue
    in_reach = [g for g in ahead if g <= served_gen + hop]
    target = max(in_reach) if in_reach else ahead[0]
    n_prom = healthz()["canary_promotions"]
    offer(archive[target])
    wait_for(lambda: not rollout_idle()
             or healthz()["canary_promotions"] > n_prom,
             90, f"rollout start for gen {target}")
    wait_for(rollout_idle, 300, f"rollout settle for gen {target}")
    if healthz()["canary_promotions"] > n_prom:
        served_gen = target
        promoted_gens.append(target)
        hop = min(hop + 1, 6)
        print(f"[chaos-soak] promoted gen {target} "
              f"(ladder {promoted_gens})", flush=True)
    else:
        # refused (low ESS against current traffic): shrink the hop and
        # retry — the gate converges the ladder, it never wedges it
        hop = max(1, hop - 1)
        print(f"[chaos-soak] gen {target} refused; hop -> {hop}",
              flush=True)
arch_stop.set()

# traffic off, then the fixed-seed AFTER measurement on the promoted fleet
for s in sims:
    s.stop(drain_timeout_s=60)
time.sleep(2)  # let in-flight tap sends land on a side of the ledger
eval_after = evaluate("after")
print(f"[chaos-soak] flywheel eval AFTER (gen {served_gen}): "
      f"{eval_after:.1f}", flush=True)
assert eval_after > eval_before + 100.0, (
    "the served policy did not improve on its own traffic",
    eval_before, eval_after)

h = healthz()
good_ev = events("canary_promote")[-1]  # the last PASSING gate verdict
good_verdict = good_ev["gate"]
router_counters = {k: h[k] for k in (
    "gate_evaluations", "gate_pass", "gate_block", "gate_stalls",
    "canary_promotions", "canary_rollbacks")}
# the gate identity (evaluations == pass + block + stalls) is asserted
# at drain by the router's ConservationLedger [flow-verdict] below
assert router_counters["gate_stalls"] >= 1, router_counters
assert router_counters["gate_block"] >= 1, router_counters
assert router_counters["gate_pass"] >= 1, router_counters
assert router_counters["canary_promotions"] >= 1, router_counters

# the tap's window ledger is asserted exact at close by the ledger's
# mirror-tap [flow-verdict] (parsed below); here just prove the chaos
# losses landed ON the books
tap = h["mirror"]
assert tap["windows_dropped_chaos"] >= 1, tap

# the ingest's per-source split: every window the learner trained on
# came from the mirror
rows = [json.loads(l) for l in open(f"{F}/metrics.jsonl")]
fleet = [r for r in rows if "fleet_windows_ingested" in r][-1]
ingest = {
    "windows_ingested": int(fleet["fleet_windows_ingested"]),
    "windows_from_mirror": int(fleet["fleet_windows_from_mirror"]),
    "windows_from_actors": int(fleet["fleet_windows_from_actors"]),
}
assert ingest["windows_from_mirror"] > 0, ingest
assert ingest["windows_from_actors"] == 0, ingest
# the per-source split identity (from_actors + from_mirror == ingested)
# is asserted at ingest close by the learner's ConservationLedger
fiv = [json.loads(l.split("[flow-verdict]", 1)[1])
       for l in learner.lines if "[flow-verdict]" in l]
fiv = [v for v in fiv if v["family"] == "fleet-ingest"]
assert fiv, "learner close emitted no fleet-ingest flow verdict"
assert all(v["ok"] for v in fiv), fiv

# graceful drains: rc 0 = guards + sentinel budgets clean everywhere
rc = router.stop(drain_timeout_s=180)
assert rc == 0, f"flywheel router exit {rc}"
# drain/close-time conservation verdicts from the router process: the
# request books, the gate verdict tally, every tenant row, and the
# mirror tap's window ledger (chaos losses on the books, pending zero)
verdicts = [json.loads(l.split("[flow-verdict]", 1)[1])
            for l in router.lines if "[flow-verdict]" in l]
for fam in ("router", "router-gate", "router-tenant", "mirror-tap"):
    fv = [v for v in verdicts if v["family"] == fam]
    assert fv, f"flywheel router drain emitted no {fam} flow verdict"
    assert all(v["ok"] for v in fv), fv
for rid in (0, 1):
    rc = reps[rid].stop(drain_timeout_s=120)
    assert rc == 0, f"flywheel replica {rid} exit {rc}"
    rv = [json.loads(l.split("[flow-verdict]", 1)[1])
          for l in reps[rid].lines if "[flow-verdict]" in l]
    sv = [v for v in rv if v["family"] == "serve-stats"]
    assert sv and all(v["ok"] for v in sv), (rid, rv)

doc = {
    "backend": "cpu",
    "schema": "flywheel-soak/v1",
    "env": "Pendulum-v1",
    "eval": {"before": round(eval_before, 2),
             "after": round(eval_after, 2),
             "episodes": 3, "seed": 12345},
    "gate": {
        "stall": {"rolled_back": True, "reason": stall_ev["reason"]},
        "bad": {"blocked": True, "verdict": bad_verdict,
                "live_error_rates": {
                    "baseline": bad_ev["baseline_error_rate"],
                    "canary": bad_ev["canary_error_rate"]}},
        "good": {"promoted": True, "verdict": good_verdict,
                 "generation": served_gen},
    },
    "promoted_generations": promoted_gens,
    "counters": {"router": router_counters, "tap": tap, "ingest": ingest},
    "identity_ok": True,
}
with open(f"{d}/flywheel_soak.json", "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
from tools.d4pglint.schema_check import check_flywheel_soak
errs = check_flywheel_soak(f"{d}/flywheel_soak.json")
assert not errs, errs

print("CHAOS_SOAK_FLYWHEEL_OK", json.dumps({
    "eval_before": round(eval_before, 1),
    "eval_after": round(eval_after, 1),
    "promoted_generations": promoted_gens,
    **router_counters,
    "tap_acked": tap["windows_acked"],
    "tap_dropped_chaos": tap["windows_dropped_chaos"],
    "ingested": ingest["windows_ingested"],
}))
EOF

# zero flywheel processes survive (learner, replicas, router, sim clients)
if pgrep -f "fleet-bundle $DIR/flywheel/lbundle" > /dev/null 2>&1 \
   || pgrep -f "d4pg_tpu.serve.*$DIR/flywheel/r" > /dev/null 2>&1 \
   || pgrep -f "d4pg_tpu.flywheel.sim_client" > /dev/null 2>&1; then
  echo "CHAOS_SOAK_FAIL: flywheel processes survived the shutdown"
  pgrep -af "$DIR/flywheel" || true
  exit 1
fi

# ---- leg 11: connection-level attack — the event-loop I/O core under -------
# slowloris / zero_window / fd_exhaust (ISSUE 20). Both tiers (router
# front-end AND a replica) run their listeners on the netio loop with
# tight eviction bounds; the chaos sites launch the attacks against each
# process's own listener at deterministic accept counts. Contracts: every
# attacker evicted (netio counters via healthz), real traffic answered
# before/during/after, the answered identity exact at drain, rc 0
# everywhere.
cp -r "$DIR/bundle" "$DIR/l11r0"
cp -r "$DIR/bundle" "$DIR/l11r1"
python - "$DIR" <<'EOF'
import json, sys, threading, time
import numpy as np

sys.path.insert(0, "scripts")
from spawnlib import spawn

d = sys.argv[1]

# Replica 0 carries its own slowloris (the replica tier is on the loop
# too); replica 1 runs clean as the control.
reps = [
    spawn([sys.executable, "-m", "d4pg_tpu.serve",
           "--bundle", f"{d}/l11r{rid}", "--port", "0",
           "--max-batch", "8", "--max-wait-us", "500",
           "--poll-interval", "0.2", "--replica-id", str(rid),
           "--io-read-stall-s", "2", "--io-write-stall-s", "2",
           "--debug-guards"]
          + (["--chaos", "seed=20;slowloris@2:50"] if rid == 0 else []),
          f"l11-replica{rid}")
    for rid in (0, 1)
]
ports = [r.wait_port(180) for r in reps]

# The router takes all three attacks. zero_window floods HEALTHZ (whose
# JSON replies are kilobytes — the backlog builds fast against a 4 KiB
# attacker rcvbuf); fd_exhaust hoards the table for 250 ms mid-service.
router = spawn(
    [sys.executable, "-m", "d4pg_tpu.serve.router",
     "--backends", ",".join(f"127.0.0.1:{p}" for p in ports),
     "--backend-bundles", f"{d}/l11r0,{d}/l11r1",
     "--port", "0", "--probe-interval", "0.2", "--readmit-after", "2",
     "--io-read-stall-s", "2", "--io-write-stall-s", "2",
     "--debug-guards",
     "--chaos", "seed=20;slowloris@3:50;zero_window@5:8000;fd_exhaust@8:250"],
    "l11-router",
)
rport = router.wait_port(120)
for _ in range(300):
    if any("admitted 2/2" in l for l in router.lines):
        break
    time.sleep(0.2)
else:
    raise SystemExit("CHAOS_SOAK_FAIL: l11 router never admitted both replicas")

from d4pg_tpu.serve.client import PolicyClient, Overloaded
from d4pg_tpu.serve.protocol import probe_healthz

obs = np.array([0.1, -0.2, 0.05], np.float32)
counts = {"ok": 0, "overloaded": 0, "error": 0}
lock = threading.Lock()
stop = threading.Event()


def load_loop():
    # one blocking chain: every act() resolves to exactly ONE outcome —
    # the client-side tally is the answered identity's left side. Each
    # reconnect (an evicted/shed client would need one) is a new accept,
    # which is also what marches the chaos sites to their trigger counts.
    while not stop.is_set():
        try:
            with PolicyClient("127.0.0.1", rport, timeout=60) as c:
                while not stop.is_set():
                    try:
                        a = c.act(obs, timeout=60)
                        assert a.shape == (1,) and abs(float(a[0])) <= 2.0, a
                        k = "ok"
                    except Overloaded:
                        k = "overloaded"
                    with lock:
                        counts[k] += 1
        except Exception:
            with lock:
                counts["error"] += 1
            time.sleep(0.1)


threads = [
    threading.Thread(target=load_loop, name=f"l11-load{i}", daemon=True)
    for i in range(4)
]
for t in threads:
    t.start()


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except OSError:
            pass  # probe landed inside the fd_exhaust hold window
        time.sleep(0.3)
    raise SystemExit(f"CHAOS_SOAK_FAIL: timed out waiting for {what}")


def netio(port):
    return probe_healthz("127.0.0.1", port, timeout_s=5.0)["netio"]


# every attacker must be evicted by the deadlines, not waited on forever
wait_for(lambda: netio(rport)["evicted_read_stall"] >= 1, 120,
         "router slowloris eviction")
wait_for(lambda: netio(rport)["evicted_write_stall"] >= 1, 120,
         "router zero-window eviction")
wait_for(lambda: netio(ports[0])["evicted_read_stall"] >= 1, 120,
         "replica 0 slowloris eviction")
print("[chaos-soak] l11: all attackers evicted", flush=True)

# service stayed interactive THROUGH the attacks and still is
with lock:
    ok_during = counts["ok"]
assert ok_during > 0, counts
with PolicyClient("127.0.0.1", rport, timeout=30) as c:
    a = c.act(obs, timeout=30)
    assert a.shape == (1,), a

time.sleep(1)  # a little more load on the post-attack fleet
stop.set()
for t in threads:
    t.join(timeout=90)
    assert not t.is_alive(), "l11 load thread wedged"

h = probe_healthz("127.0.0.1", rport, timeout_s=5.0)
submitted = sum(counts.values())
assert counts["ok"] > 0 and submitted > 0, counts

# drains: rc 0 = guards + ledger clean; the [flow-verdict] lines are the
# router-side answered identity (requests_total == ok+overloaded+error)
rc = router.stop(drain_timeout_s=120)
assert rc == 0, f"l11 router exit {rc}"
verdicts = [json.loads(l.split("[flow-verdict]", 1)[1])
            for l in router.lines if "[flow-verdict]" in l]
for fam in ("router", "router-tenant"):
    fv = [v for v in verdicts if v["family"] == fam]
    assert fv, f"l11 router drain emitted no {fam} flow verdict"
    assert all(v["ok"] for v in fv), fv
for rid in (0, 1):
    rc = reps[rid].stop(drain_timeout_s=120)
    assert rc == 0, f"l11 replica {rid} exit {rc}"
    rv = [json.loads(l.split("[flow-verdict]", 1)[1])
          for l in reps[rid].lines if "[flow-verdict]" in l]
    sv = [v for v in rv if v["family"] == "serve-stats"]
    assert sv and all(v["ok"] for v in sv), (rid, rv)

print("CHAOS_SOAK_NETIO_OK", json.dumps({
    "submitted": submitted, **counts,
    "router_netio": {k: h["netio"][k] for k in (
        "conns_total", "evicted_read_stall", "evicted_write_stall",
        "accept_shed", "accept_backoffs")},
}))
EOF

# zero leg-11 processes survive
if pgrep -f "d4pg_tpu.serve.*$DIR/l11r" > /dev/null 2>&1; then
  echo "CHAOS_SOAK_FAIL: leg-11 processes survived the shutdown"
  pgrep -af "$DIR/l11r" || true
  exit 1
fi

echo "CHAOS_SOAK_OK"
