#!/usr/bin/env bash
# Chaos soak: the self-healing contracts end to end through the REAL CLIs
# (docs/fault_tolerance.md). The loop:
#
#   1. train under injected faults (env crash + worker SIGKILL + flusher
#      stall) with --debug-guards — must exit 0 with restarts logged;
#   2. start a checkpointing run, kill -9 it at a random instant;
#   3. --resume — must come back rc 0 on the newest intact step (the
#      crash-consistency manifest contract), finishing the step budget;
#   4. export a bundle and serve it under an injected client socket
#      reset — server must answer before AND after, then drain on
#      SIGTERM with exit 0.
#
# Knobs (env vars): SOAK_DIR (default mktemp), SOAK_ENV (Pendulum-v1),
# SOAK_STEPS (grad steps per leg, default 6), SOAK_HIDDEN (16,16),
# SOAK_KILL_DELAY_MAX (seconds after first commit, default 2).
# Exits non-zero on the first broken contract.
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=${SOAK_DIR:-$(mktemp -d /tmp/chaos_soak.XXXXXX)}
ENV_ID=${SOAK_ENV:-Pendulum-v1}
STEPS=${SOAK_STEPS:-6}
HIDDEN=${SOAK_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

common=(--env "$ENV_ID" --hidden-sizes "$HIDDEN" --warmup 24 --bsize 8
        --rmsize 512 --eval-interval 100000 --num-envs 2
        --pool-start-method fork --snapshot-replay)

echo "[chaos-soak] dir: $DIR"

# ---- leg 1: train THROUGH injected faults, guards on -----------------------
python train.py "${common[@]}" --log-dir "$DIR/faulty" \
  --total-steps "$STEPS" --checkpoint-interval "$STEPS" \
  --debug-guards --async-writeback --pool-step-timeout 15 \
  --chaos "seed=3;env_raise@5#0;worker_kill@9#1;wb_stall@1:0.2" \
  | tee "$DIR/faulty.log"
grep -q "worker_restart" "$DIR/faulty.log" \
  || { echo "CHAOS_SOAK_FAIL: no worker restart under injected faults"; exit 1; }

# ---- leg 2: kill -9 a checkpointing run at a random instant ----------------
python train.py "${common[@]}" --log-dir "$DIR/killed" \
  --total-steps 100000 --checkpoint-interval 4 \
  > "$DIR/killed.log" 2>&1 &
PID=$!
CKPT="$DIR/killed/checkpoints"
for _ in $(seq 1 600); do
  compgen -G "$CKPT/manifest_*.json" > /dev/null && break
  kill -0 "$PID" 2>/dev/null || { cat "$DIR/killed.log"; echo "CHAOS_SOAK_FAIL: run died before first commit"; exit 1; }
  sleep 0.5
done
compgen -G "$CKPT/manifest_*.json" > /dev/null \
  || { echo "CHAOS_SOAK_FAIL: no checkpoint committed"; exit 1; }
# randomized instant within the next interval: mid-save, mid-snapshot, between
sleep "0.$((RANDOM % 100))"; sleep "$((RANDOM % ${SOAK_KILL_DELAY_MAX:-2}))"
kill -9 "$PID" || true
wait "$PID" 2>/dev/null || true
echo "[chaos-soak] killed training at a random instant"

# ---- leg 3: resume must restore the newest intact step ---------------------
NEWEST=$(ls "$CKPT"/manifest_*.json | sed 's/.*manifest_\([0-9]*\).json/\1/' | sort -n | tail -1)
python train.py "${common[@]}" --log-dir "$DIR/killed" --resume \
  --total-steps $((NEWEST + 4)) --checkpoint-interval 4 \
  | tee "$DIR/resume.log"
grep -q "\[checkpoint\] resumed from step" "$DIR/resume.log" \
  || { echo "CHAOS_SOAK_FAIL: resume did not report its restored step"; exit 1; }

# ---- leg 4: serve the survivor under an injected socket reset --------------
python train.py --env "$ENV_ID" --hidden-sizes "$HIDDEN" \
  --log-dir "$DIR/killed" --export-bundle "$DIR/bundle"
python - "$DIR/bundle" <<'EOF'
import signal, subprocess, sys, numpy as np
bundle = sys.argv[1]
proc = subprocess.Popen(
    [sys.executable, "-m", "d4pg_tpu.serve", "--bundle", bundle,
     "--port", "0", "--max-batch", "8", "--max-wait-us", "500",
     "--chaos", "sock_reset@2"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
port = None
for line in proc.stdout:
    sys.stdout.write("[server] " + line)
    if "listening on" in line:
        port = int(line.split(":")[1].split()[0])
        break
assert port, "server never reported its port"
from d4pg_tpu.serve.client import PolicyClient
obs = np.array([0.1, -0.2, 0.05], np.float32)
with PolicyClient("127.0.0.1", port) as c:
    assert c.act(obs).shape == (1,)      # frame 1: served
    try:
        c.act(obs)                       # frame 2: injected reset
        raise SystemExit("CHAOS_SOAK_FAIL: injected reset never fired")
    except Exception:
        pass
with PolicyClient("127.0.0.1", port) as c:   # server survived the reset
    assert c.act(obs).shape == (1,)
    h = c.healthz()
    assert h.get("chaos_injections") == 1, h
proc.send_signal(signal.SIGTERM)
tail = proc.stdout.read()
sys.stdout.write("[server] " + tail)
rc = proc.wait(timeout=120)
assert rc == 0 and "drained" in tail, (rc, tail)
print("CHAOS_SOAK_SERVE_OK")
EOF

echo "CHAOS_SOAK_OK"
