#!/usr/bin/env bash
# Router smoke: the replicated-serving path end to end through the real
# CLIs — train → export-bundle → TWO serve replicas (--replica-id) →
# router front-end → roundtrips → kill -9 one replica → roundtrips keep
# succeeding through the failover → graceful drains. Wired into tier-1 via
# tests/test_router_smoke.py; also runnable by hand:
#
#   scripts/router_smoke.sh
#   ROUTER_SMOKE_DIR=/tmp/x scripts/router_smoke.sh
#
# Knobs (env vars): ROUTER_SMOKE_DIR (run dir, default mktemp),
# ROUTER_SMOKE_STEPS (grad steps, default 2), ROUTER_SMOKE_HIDDEN
# (MLP widths, default 16,16).
#
# Asserts: the router admits both replicas; requests through the router
# answer inside the env's bounds; after a replica SIGKILL the survivors
# keep answering (health-driven ejection + bounded failover), the router's
# healthz records the ejection AND the accounting identity (answered ==
# submitted); both the router and the surviving replica drain rc 0.
set -euo pipefail
cd "$(dirname "$0")/.."

RUN=${ROUTER_SMOKE_DIR:-$(mktemp -d /tmp/router_smoke.XXXXXX)}
STEPS=${ROUTER_SMOKE_STEPS:-2}
HIDDEN=${ROUTER_SMOKE_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

echo "[router-smoke] run dir: $RUN"
python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --total-steps "$STEPS" --warmup 16 --bsize 8 --rmsize 512 \
  --eval-interval "$STEPS" --eval-episodes 2 \
  --checkpoint-interval "$STEPS" --num-envs 1 \
  --log-dir "$RUN"

python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --log-dir "$RUN" --export-bundle "$RUN/bundle"

# each replica serves its OWN bundle dir (the canary controller's contract:
# the router rolls a replica forward by writing into its dir)
cp -r "$RUN/bundle" "$RUN/replica0"
cp -r "$RUN/bundle" "$RUN/replica1"

python - "$RUN" <<'EOF'
import signal, sys, time
import numpy as np

sys.path.insert(0, "scripts")
from spawnlib import spawn

run = sys.argv[1]

replicas = []
for rid in (0, 1):
    replicas.append(
        spawn(
            [sys.executable, "-m", "d4pg_tpu.serve",
             "--bundle", f"{run}/replica{rid}", "--port", "0",
             "--max-batch", "8", "--max-wait-us", "500",
             "--replica-id", str(rid)],
            f"replica{rid}",
        )
    )
ports = [r.wait_port(120) for r in replicas]

router = spawn(
    [sys.executable, "-m", "d4pg_tpu.serve.router",
     "--backends", ",".join(f"127.0.0.1:{p}" for p in ports),
     "--backend-bundles", f"{run}/replica0,{run}/replica1",
     "--port", "0", "--probe-interval", "0.2", "--readmit-after", "2"],
    "router",
)
rport = router.wait_port(120)
for _ in range(600):
    if any("admitted 2/2" in l for l in router.lines):
        break
    time.sleep(0.2)
else:
    raise SystemExit("ROUTER_SMOKE_FAIL: router never admitted both replicas")

from d4pg_tpu.serve.client import PolicyClient

obs = np.array([0.1, -0.2, 0.05], np.float32)
with PolicyClient("127.0.0.1", rport) as c:
    for _ in range(8):
        a = c.act(obs, timeout=30)
        assert a.shape == (1,) and abs(float(a[0])) <= 2.0, a
    h = c.healthz()
    assert h["router"] is True and h["admitted"] == 2, h
    # --replica-id flows through healthz into the router's fleet view
    assert sorted(r["replica_id"] for r in h["replicas"]) == [0, 1], h

    # ---- kill -9 replica 0 mid-fleet: ejection + failover ------------------
    replicas[0].proc.kill()
    for _ in range(16):  # requests keep succeeding THROUGH the failure
        a = c.act(obs, timeout=30)
        assert a.shape == (1,) and abs(float(a[0])) <= 2.0, a
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        h = c.healthz()
        if h["admitted"] == 1:
            break
        time.sleep(0.2)
    assert h["admitted"] == 1, h
    dead = next(r for r in h["replicas"] if not r["admitted"])
    assert dead["replica_id"] == 0 and dead["ejected_reason"], dead
    # accounting identity: every ACT answered, none silently lost
    # (healthz frames don't count — 8 pre-kill + 16 through the failure)
    assert h["requests_total"] == h["answered_total"] == 24, h
    assert h["replies_error"] == 0, h

# graceful drains via the shared bounded SIGTERM->SIGKILL escalation
# (spawnlib.Spawned.stop): a drain-deaf process gets reaped, not hung on
rc = router.stop(drain_timeout_s=120)
assert rc == 0, f"router exit code {rc}"
assert any("drained" in l for l in router.lines), router.lines[-5:]

rc = replicas[1].stop(drain_timeout_s=120)
assert rc == 0, f"surviving replica exit code {rc}"
replicas[0].proc.wait(timeout=30)
print("ROUTER_SMOKE_ROUNDTRIP_OK")
EOF

echo "ROUTER_SMOKE_OK"
