#!/usr/bin/env bash
# Serve smoke: the full train → export-bundle → serve → round-trip → drain
# path on CPU, end to end through the real CLIs. Wired into tier-1 via
# tests/test_serve_smoke.py; also runnable by hand:
#
#   scripts/serve_smoke.sh            # throwaway run dir
#   SERVE_SMOKE_DIR=/tmp/x scripts/serve_smoke.sh
#
# Knobs (env vars): SERVE_SMOKE_DIR (run dir, default mktemp),
# SERVE_SMOKE_STEPS (grad steps, default 2), SERVE_SMOKE_HIDDEN
# (MLP widths, default 16,16 — tiny so the CPU compile stays seconds).
#
# Asserts: a checkpointed short training run exports a bundle; the server
# answers one observation with an action inside the env's bounds; SIGTERM
# drains cleanly (exit 0 with the drained summary line).
set -euo pipefail
cd "$(dirname "$0")/.."

RUN=${SERVE_SMOKE_DIR:-$(mktemp -d /tmp/serve_smoke.XXXXXX)}
STEPS=${SERVE_SMOKE_STEPS:-2}
HIDDEN=${SERVE_SMOKE_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

echo "[serve-smoke] run dir: $RUN"
python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --total-steps "$STEPS" --warmup 16 --bsize 8 --rmsize 512 \
  --eval-interval "$STEPS" --eval-episodes 2 \
  --checkpoint-interval "$STEPS" --num-envs 1 \
  --log-dir "$RUN"

python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --log-dir "$RUN" --export-bundle "$RUN/bundle"

python - "$RUN/bundle" <<'EOF'
import os, signal, subprocess, sys, numpy as np
bundle = sys.argv[1]
proc = subprocess.Popen(
    [sys.executable, "-m", "d4pg_tpu.serve", "--bundle", bundle,
     "--port", "0", "--max-batch", "8", "--max-wait-us", "500"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
port = None
for line in proc.stdout:
    sys.stdout.write("[server] " + line)
    if "listening on" in line:
        port = int(line.split(":")[1].split()[0])
        break
assert port, "server never reported its port"
from d4pg_tpu.serve.client import PolicyClient
with PolicyClient("127.0.0.1", port) as c:
    a = c.act(np.array([0.1, -0.2, 0.05], np.float32))
    # Pendulum-v1 torque bounds (the bundle carries them): env-scale output
    assert a.shape == (1,) and abs(float(a[0])) <= 2.0, a
    h = c.healthz()
    assert h["status"] == "ok" and h["replies_ok"] >= 1, h
proc.send_signal(signal.SIGTERM)
tail = proc.stdout.read()
sys.stdout.write("[server] " + tail)
rc = proc.wait(timeout=120)
assert rc == 0, f"server exit code {rc}"
assert "drained" in tail, tail
print("SERVE_SMOKE_ROUNDTRIP_OK")
EOF

echo "SERVE_SMOKE_OK"
