"""Shared subprocess harness for the CLI smoke/soak scripts' python legs.

One copy on purpose (the ``clean_cpu_env`` / ``abortive_close`` dedup
precedent): both ``scripts/router_smoke.sh`` and the chaos-soak router
leg spawn serve/router CLIs, pump their stdout through a tagged tee, and
scrape the ``listening on host:port`` startup line for the ephemeral
port. The heredocs run from the repo root, so they import this with::

    sys.path.insert(0, "scripts"); from spawnlib import spawn

ISSUE 15: the kill-9/process-group handling that used to be copy-pasted
per harness lives here now, backed by ``d4pg_tpu.utils.procs``:

- :func:`spawn_group` starts the child as its OWN session/group leader
  (setsid), so (a) killing it can take its whole subtree (a learner's
  pool workers) and (b) it survives the spawner's death — the league
  controller's re-adopt-after-kill-9 contract;
- :meth:`Spawned.stop` is THE bounded escalation (SIGTERM drain →
  bounded wait → group SIGKILL → sweep);
- :func:`reap_orphans` sweeps every group this module ever spawned and
  returns the survivors it had to kill ([] is the "zero orphaned
  processes" assertion).
"""

import signal
import subprocess
import sys
import threading

from d4pg_tpu.utils import procs


# Every process group spawn_group() created in this process, for the
# final reap_orphans() sweep (pgid of a setsid child == its pid).
_GROUP_PGIDS = []


class Spawned:
    """A CLI subprocess with a stdout pump thread: ``lines`` collects
    everything printed (tagged onto our stdout as it arrives), and the
    first ``listening on host:port`` line parses into ``wait_port()``."""

    def __init__(self, argv, tag, new_session=False, env=None):
        self.tag = tag
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            start_new_session=new_session, env=env,
        )
        # setsid children lead their own group (pgid == pid); plain spawns
        # share ours — stop() then escalates on the leader only.
        self.pgid = self.proc.pid if new_session else 0
        if new_session:
            _GROUP_PGIDS.append(self.pgid)
        self.lines = []
        self.port_event = threading.Event()
        self._port_box = []
        threading.Thread(
            target=self._pump, name=f"pump-{tag}", daemon=True
        ).start()

    def _pump(self):
        for line in self.proc.stdout:
            sys.stdout.write(f"[{self.tag}] {line}")
            self.lines.append(line)
            if "listening on" in line and not self._port_box:
                addr = line.split("listening on", 1)[1].split()[0]
                self._port_box.append(int(addr.rsplit(":", 1)[1]))
                self.port_event.set()
        self.port_event.set()  # EOF: don't leave a waiter hanging

    def wait_port(self, timeout=180.0):
        assert self.port_event.wait(timeout) and self._port_box, (
            f"{self.tag} never reported its port"
        )
        return self._port_box[0]

    def stop(self, sig=signal.SIGTERM, drain_timeout_s=120.0,
             kill_timeout_s=10.0):
        """Bounded stop: ``sig`` → wait ``drain_timeout_s`` → SIGKILL the
        group (setsid spawns) / leader → bounded reap. Returns the exit
        code (None only if the kill itself wedged)."""
        rc = procs.drain_or_kill(
            self.proc, pgid=self.pgid, sig=sig,
            drain_timeout_s=drain_timeout_s, kill_timeout_s=kill_timeout_s,
            label=self.tag,
        )
        if self.pgid and not procs.group_pids(self.pgid):
            # confirmed empty: drop it from the sweep registry so a
            # kernel-recycled pgid can never be group-killed later
            try:
                _GROUP_PGIDS.remove(self.pgid)
            except ValueError:
                pass
        return rc


def spawn(argv, tag, env=None):
    return Spawned(argv, tag, env=env)


def spawn_group(argv, tag, env=None):
    """Spawn as a session/process-group leader (setsid): kills can take
    the whole subtree, and the child outlives this process."""
    return Spawned(argv, tag, new_session=True, env=env)


def reap_orphans():
    """SIGKILL any survivor in any group this process spawned via
    :func:`spawn_group`; returns the PIDs that were still alive. Callers
    with a zero-orphans contract assert the return is empty."""
    return procs.reap_orphans(list(_GROUP_PGIDS), label="spawnlib")
