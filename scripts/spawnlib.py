"""Shared subprocess harness for the CLI smoke/soak scripts' python legs.

One copy on purpose (the ``clean_cpu_env`` / ``abortive_close`` dedup
precedent): both ``scripts/router_smoke.sh`` and the chaos-soak router
leg spawn serve/router CLIs, pump their stdout through a tagged tee, and
scrape the ``listening on host:port`` startup line for the ephemeral
port. The heredocs run from the repo root, so they import this with::

    sys.path.insert(0, "scripts"); from spawnlib import spawn
"""

import subprocess
import sys
import threading


class Spawned:
    """A CLI subprocess with a stdout pump thread: ``lines`` collects
    everything printed (tagged onto our stdout as it arrives), and the
    first ``listening on host:port`` line parses into ``wait_port()``."""

    def __init__(self, argv, tag):
        self.tag = tag
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )
        self.lines = []
        self.port_event = threading.Event()
        self._port_box = []
        threading.Thread(
            target=self._pump, name=f"pump-{tag}", daemon=True
        ).start()

    def _pump(self):
        for line in self.proc.stdout:
            sys.stdout.write(f"[{self.tag}] {line}")
            self.lines.append(line)
            if "listening on" in line and not self._port_box:
                addr = line.split("listening on", 1)[1].split()[0]
                self._port_box.append(int(addr.rsplit(":", 1)[1]))
                self.port_event.set()
        self.port_event.set()  # EOF: don't leave a waiter hanging

    def wait_port(self, timeout=180.0):
        assert self.port_event.wait(timeout) and self._port_box, (
            f"{self.tag} never reported its port"
        )
        return self._port_box[0]


def spawn(argv, tag):
    return Spawned(argv, tag)
