#!/usr/bin/env bash
# League smoke: the PBT controller lifecycle end to end through the REAL
# CLI (python -m d4pg_tpu.league) — seeded 3-variant league with fitness
# separation baked into the genomes, one full exploit/explore generation
# (cull worst → manifest-verified checkpoint fork → perturbed clone →
# attest → promote), then a controller kill -9 MID-GENERATION (chaos
# controller_kill) and a rerun that must resume the SAME generation,
# re-adopt the surviving learners, and finish with zero orphans and the
# accounting identity exact (league_summary.json is schema-gated).
#
# Learners are scripts/league_stub_learner.py — the deterministic
# train.py stand-in that speaks the league surface (real manifests, real
# exit-75 drains, real trainer_meta attestation) in milliseconds, which
# is what keeps this inside the tier-1 60 s clock guard
# (tests/test_league_smoke.py asserts the budget). The REAL-train.py
# league runs in chaos_soak.sh leg 9.
#
# Knobs (env vars): LEAGUE_SMOKE_DIR (default mktemp).
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=${LEAGUE_SMOKE_DIR:-$(mktemp -d /tmp/league_smoke.XXXXXX)}
mkdir -p "$DIR"
echo "[league-smoke] dir: $DIR"

league_args=(--seed 7 --poll-interval 0.1 --gen-timeout 60
             --drain-timeout 20 --attest-timeout 20 --observe-timeout 20
             --genome 'lr_actor=1e-4,max_episode_steps=50'
             --genome 'lr_actor=1e-4,max_episode_steps=200'
             --genome 'lr_actor=1e-3,max_episode_steps=250')
stub=(python scripts/league_stub_learner.py
      --checkpoint-interval 4 --eval-interval 2 --tick-seconds 0.03)

# ---- leg 1: kill -9 the controller mid-generation (chaos site), rerun ------
# controller_kill@6 lands inside the first generation's apply window
# (plan ~tick 2-3, fork/observe span several ticks).
set +e
python -m d4pg_tpu.league --dir "$DIR/league" "${league_args[@]}" \
  --generations 2 --chaos "seed=5;controller_kill@6" \
  -- "${stub[@]}" | tee "$DIR/leg1.log"
RC=${PIPESTATUS[0]}
set -e
grep -q "controller_kill: SIGKILL self" "$DIR/leg1.log" \
  || { echo "LEAGUE_SMOKE_FAIL: controller_kill never fired"; exit 1; }
[ "$RC" -ne 0 ] || { echo "LEAGUE_SMOKE_FAIL: SIGKILLed controller exited 0"; exit 1; }
GEN_AT_CRASH=$(python -c "import json;print(json.load(open('$DIR/league/league.json'))['generation'])")
echo "[league-smoke] controller killed at generation $GEN_AT_CRASH"

# ---- leg 2: the rerun resumes the SAME generation and finishes -------------
python -m d4pg_tpu.league --dir "$DIR/league" "${league_args[@]}" \
  --generations 2 \
  -- "${stub[@]}" | tee "$DIR/leg2.log"
grep -q "journal_resumed" "$DIR/leg2.log" \
  || { echo "LEAGUE_SMOKE_FAIL: rerun did not resume the journal"; exit 1; }

# ---- asserts: promotion of the planted winner, identity, zero orphans ------
python - "$DIR" "$GEN_AT_CRASH" <<'EOF'
import json, os, sys
d, gen_at_crash = sys.argv[1], int(sys.argv[2])
s = json.load(open(f"{d}/league/league_summary.json"))
assert s["generations_completed"] == 2, s["generations_completed"]
assert s["promotions"] >= 1, s
# every clone in the lineage descends from the planted winner (uid 1:
# lr 1e-4 @ 50-step horizon — the deterministically-best genome)
def root(uid, variants):
    while variants[str(uid)]["parent"] is not None:
        uid = variants[str(uid)]["parent"]
    return uid
clones = [e for e in s["lineage"] if e["reason"] == "clone"]
assert clones and all(root(e["parent"], s["variants"]) == 1 for e in clones), \
    s["lineage"]
# the planted winner's bloodline holds the majority of final slots
final = [root(uid, s["variants"]) for uid in s["members"].values()]
assert final.count(1) >= 2, (final, s["members"])
# crash consistency: the rerun resumed the generation the crash left
# in flight (leg2's journal_resumed) and never double-booked it
events = [json.loads(l) for l in open(f"{d}/league/league_events.jsonl")]
done = [e for e in events if e["event"] == "generation_done"]
gens = [e["gen"] for e in done]
assert sorted(set(gens)) == gens, f"a generation committed twice: {gens}"
# accounting identity + zero orphans, via the committed-artifact gate
sys.path.insert(0, ".")
from tools.d4pglint.schema_check import check_league_soak
errs = check_league_soak(f"{d}/league/league_summary.json")
assert not errs, errs
assert s["identity_ok"] is True and s["orphans_swept"] == 0
print("LEAGUE_SMOKE_ASSERTS_OK",
      {"generations": s["generations_completed"],
       "promotions": s["promotions"], "rollbacks": s["rollbacks"],
       "crash_gen": gen_at_crash})
EOF

# zero orphaned learner processes (the /proc scan the controller also
# performs at shutdown — belt and suspenders at the script level)
if pgrep -f "league_stub_learner.*$DIR" > /dev/null 2>&1; then
  echo "LEAGUE_SMOKE_FAIL: orphaned stub learners survived"
  pgrep -af "league_stub_learner.*$DIR" || true
  exit 1
fi

echo "LEAGUE_SMOKE_OK"
