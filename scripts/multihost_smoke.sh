#!/usr/bin/env bash
# Multi-host smoke (ISSUE 17): the 2-process × 4-device virtual mesh end
# to end through the REAL CLI — bring-up (jax.distributed over gloo,
# per-host ingest, --debug-guards on so a guard trip or leaked hold is a
# hard failure), then the host_kill chaos site: SIGKILL one process of
# the mesh MID-TRAINING, reap the blocked survivor (the supervisor's
# move — a half-dead mesh cannot make progress past its next
# collective), and prove the full-mesh relaunch resumes from the last
# COMMITTED coordinated checkpoint (manifest-attested step 12, not the
# in-flight work the kill destroyed) with the replay snapshot and
# device-PER sidecar restored and bit-identical done-lines on both
# processes.
#
# Every leg spawns real train.py processes with a cold compile, so the
# whole script is slow-tier: tests/test_multihost_smoke.py wraps it
# @pytest.mark.slow per the tier-1 clock-guard convention (long legs are
# slow-marked; nothing from this smoke runs inside the 60 s fast tier).
#
# Knobs (env vars): MULTIHOST_SMOKE_DIR (default mktemp).
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=${MULTIHOST_SMOKE_DIR:-$(mktemp -d /tmp/multihost_smoke.XXXXXX)}
mkdir -p "$DIR"
echo "[multihost-smoke] dir: $DIR"

PORT=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
EOF
)

RUN="$DIR/run"
common=(--env pendulum --hidden-sizes 16,16 --n-atoms 11
        --warmup 24 --bsize 8 --rmsize 256
        --dp 8 --replay-placement device --num-envs 2
        --eval-interval 100000 --eval-episodes 1
        --checkpoint-interval 12 --snapshot-replay --no-concurrent-eval
        --debug-guards --log-dir "$RUN" --seed 3
        --coordinator "localhost:$PORT" --num-processes 2)

# launch <rank> [args...]: spawns a mesh process in THIS shell (no
# command substitution — the pid must stay wait-able) and reports it in
# LAST_PID.
launch() {
  local rank=$1; shift
  env JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
      python train.py "${common[@]}" --process-id "$rank" "$@" \
      > "$DIR/leg${LEG}_p${rank}.log" 2>&1 &
  LAST_PID=$!
}

# ---- leg 1: host_kill@18:1 — SIGKILL process 1 at megastep dispatch 18 -----
# Checkpoint 12 commits first (its gather is a collective, both processes
# alive), so the kill lands strictly between the committed step and the
# next one — the work it destroys must NOT be resumed into.
LEG=1
launch 0 --total-steps 48 --chaos "host_kill@18:1"; P0=$LAST_PID
launch 1 --total-steps 48 --chaos "host_kill@18:1"; P1=$LAST_PID
set +e
wait "$P1"; RC1=$?
set -e
grep -q "host_kill: SIGKILL process 1" "$DIR/leg1_p1.log" \
  || { echo "MULTIHOST_SMOKE_FAIL: host_kill never fired"; tail -20 "$DIR/leg1_p1.log"; exit 1; }
[ "$RC1" -ne 0 ] || { echo "MULTIHOST_SMOKE_FAIL: SIGKILLed process exited 0"; exit 1; }
echo "[multihost-smoke] victim (process 1) died rc=$RC1"
# The survivor is wedged on its next cross-process collective — a mesh
# with a dead member cannot make progress. Reap it, as a supervisor
# (or the pod scheduler) would, then relaunch the FULL mesh.
kill -9 "$P0" 2>/dev/null || true
set +e; wait "$P0" 2>/dev/null; set -e
echo "[multihost-smoke] survivor (process 0) reaped"
ls "$RUN/checkpoints/" | grep -q "manifest_12.json" \
  || { echo "MULTIHOST_SMOKE_FAIL: no committed checkpoint before the kill"; exit 1; }

# ---- leg 2: full-mesh relaunch --resume -----------------------------------
LEG=2
launch 0 --total-steps 24 --resume; P0=$LAST_PID
launch 1 --total-steps 24 --resume; P1=$LAST_PID
wait "$P0" "$P1"
for rank in 0 1; do
  L="$DIR/leg2_p$rank.log"
  grep -q "resumed from step 12" "$L" \
    || { echo "MULTIHOST_SMOKE_FAIL: p$rank did not resume from the committed step"; tail -20 "$L"; exit 1; }
  grep -q "restored replay snapshot" "$L" \
    || { echo "MULTIHOST_SMOKE_FAIL: p$rank did not restore the replay snapshot"; exit 1; }
  grep -q "restored device-PER priorities" "$L" \
    || { echo "MULTIHOST_SMOKE_FAIL: p$rank did not restore the PER sidecar"; exit 1; }
  grep -q "^done:" "$L" \
    || { echo "MULTIHOST_SMOKE_FAIL: p$rank did not complete"; tail -20 "$L"; exit 1; }
done
# One SPMD program, one answer: every MODEL metric in the two processes'
# done-lines must be bit-identical (the *_per_sec rates are per-process
# wall clock and legitimately differ). --debug-guards was on for every
# leg, so completion also attests zero guard trips and zero leaked holds.
python - "$DIR/leg2_p0.log" "$DIR/leg2_p1.log" <<'EOF'
import ast, sys
dicts = []
for path in sys.argv[1:]:
    line = next(l for l in reversed(open(path).read().splitlines())
                if l.startswith("done:"))
    dicts.append(ast.literal_eval(line[len("done:"):].strip()))
model = [{k: v for k, v in d.items() if not k.endswith("_per_sec")}
         for d in dicts]
assert model[0] == model[1], ("done-lines differ across the mesh",
                              model[0], model[1])
print("MULTIHOST_SMOKE_ASSERTS_OK",
      {"resumed_from": 12,
       "final_critic_loss": model[0]["critic_loss"],
       "final_grad_steps": 36})
EOF

# zero orphaned mesh processes
if pgrep -f "train.py.*$RUN" > /dev/null 2>&1; then
  echo "MULTIHOST_SMOKE_FAIL: orphaned mesh processes survived"
  pgrep -af "train.py.*$RUN" || true
  exit 1
fi

echo "MULTIHOST_SMOKE_OK"
