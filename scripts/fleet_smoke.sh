#!/usr/bin/env bash
# Fleet smoke: the 2-process collection fleet end to end through the REAL
# CLIs (docs/fleet.md). Wired into tier-1 via tests/test_fleet_smoke.py;
# also runnable by hand:
#
#   scripts/fleet_smoke.sh                 # throwaway run dir
#   FLEET_SMOKE_DIR=/tmp/x scripts/fleet_smoke.sh
#
# The flow:
#   1. train.py --fleet-listen 0 --fleet-bundle --num-envs 0 --debug-guards:
#      the learner runs the experience-ingest server and publishes the
#      acting bundle — it has NO local collection, so it can only finish
#      if the fleet supplies real windows (the pacing proves ingest);
#   2. python -m d4pg_tpu.fleet.actor connects, streams windows, and
#      hot-swaps the bundle as the trainer re-publishes generations
#      mid-run (the mtime-attested weight-distribution path);
#   3. learner completes rc 0 (guards green — a sentinel/ledger/transfer
#      trip would have raised); the actor is then SIGTERM'd and must
#      drain rc 0 with every emitted window accounted for.
#
# Knobs (env vars): FLEET_SMOKE_DIR, FLEET_SMOKE_STEPS (default 60),
# FLEET_SMOKE_HIDDEN (default 16,16).
set -euo pipefail
cd "$(dirname "$0")/.."

RUN=${FLEET_SMOKE_DIR:-$(mktemp -d /tmp/fleet_smoke.XXXXXX)}
mkdir -p "$RUN"
STEPS=${FLEET_SMOKE_STEPS:-60}
HIDDEN=${FLEET_SMOKE_HIDDEN:-16,16}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

echo "[fleet-smoke] run dir: $RUN"

python train.py --env Pendulum-v1 --hidden-sizes "$HIDDEN" \
  --total-steps "$STEPS" --warmup 24 --bsize 8 --rmsize 512 \
  --eval-interval "$STEPS" --eval-episodes 2 \
  --checkpoint-interval "$STEPS" --num-envs 0 \
  --fleet-listen 0 --fleet-bundle "$RUN/bundle" \
  --fleet-publish-interval 20 --debug-guards \
  --log-dir "$RUN" > "$RUN/learner.log" 2>&1 &
LEARNER=$!

PORT=
for _ in $(seq 1 600); do
  PORT=$(sed -n 's/.*ingest listening on :\([0-9][0-9]*\).*/\1/p' "$RUN/learner.log" | head -1)
  if [ -n "$PORT" ] && [ -f "$RUN/bundle/bundle.json" ]; then break; fi
  kill -0 "$LEARNER" 2>/dev/null \
    || { cat "$RUN/learner.log"; echo "FLEET_SMOKE_FAIL: learner died before listening"; exit 1; }
  sleep 0.2
done
[ -n "$PORT" ] || { cat "$RUN/learner.log"; echo "FLEET_SMOKE_FAIL: no ingest port"; exit 1; }
echo "[fleet-smoke] ingest on :$PORT"

python -m d4pg_tpu.fleet.actor --connect "127.0.0.1:$PORT" \
  --bundle "$RUN/bundle" --batch-windows 8 --poll-interval 0.3 \
  --stats-interval 5 --seed 11 > "$RUN/actor.log" 2>&1 &
ACTOR=$!

# The learner can only complete because the actor feeds it (fleet-only
# pacing): its rc 0 IS the ingest proof, and --debug-guards means any
# recompile/transfer/staging trip would have raised instead.
if ! wait "$LEARNER"; then
  cat "$RUN/learner.log"; kill -9 "$ACTOR" 2>/dev/null || true
  echo "FLEET_SMOKE_FAIL: learner exited non-zero"; exit 1
fi
grep -q "published bundle generation 1" "$RUN/learner.log" \
  || { cat "$RUN/learner.log"; echo "FLEET_SMOKE_FAIL: no mid-run bundle publish"; exit 1; }

# Give the actor one more poll so it observes the final published bundle
# (the hot-swap-mid-run assertion below), then SIGTERM-drain it.
sleep 1.2
kill -TERM "$ACTOR"
if ! wait "$ACTOR"; then
  cat "$RUN/actor.log"; echo "FLEET_SMOKE_FAIL: actor drain exited non-zero"; exit 1
fi
grep -q "hot-swapped bundle generation=" "$RUN/actor.log" \
  || { cat "$RUN/actor.log"; echo "FLEET_SMOKE_FAIL: actor never hot-swapped the bundle"; exit 1; }
grep -q "\[fleet-actor\] drained:" "$RUN/actor.log" \
  || { cat "$RUN/actor.log"; echo "FLEET_SMOKE_FAIL: actor never drained"; exit 1; }

# Window accounting: real windows ingested, and every emitted window
# accounted for (acked + stale + shed + dropped + still-spooled) — the
# zero-torn-windows contract, checked from the artifacts the run left.
python - "$RUN" <<'EOF'
import ast, json, sys
run = sys.argv[1]
rows = [json.loads(l) for l in open(f"{run}/metrics.jsonl")]
fleet_rows = [r for r in rows if "fleet_windows_ingested" in r]
assert fleet_rows, "no metrics row carries fleet counters"
last = fleet_rows[-1]
assert last["fleet_windows_ingested"] > 0, last
assert last["fleet_generation"] >= 1, last
drained = [l for l in open(f"{run}/actor.log") if "drained:" in l][-1]
stats = ast.literal_eval(drained.split("drained:", 1)[1].strip())
acct = (stats["windows_acked"] + stats["windows_stale"] + stats["windows_shed"]
        + stats["windows_dropped_reconnect"] + stats["windows_dropped_spool"]
        + stats["spool_depth"])
assert acct == stats["windows_emitted"], (acct, stats)
print("FLEET_SMOKE_COUNTERS_OK", {k: stats[k] for k in
      ("windows_emitted", "windows_acked", "bundle_reloads")})
EOF

echo "FLEET_SMOKE_OK"
