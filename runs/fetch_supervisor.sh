#!/bin/bash
# Goal-dict robotics (gymnasium_robotics Fetch family) training legs —
# sparse reward + HER, the env family the reference's active loop is
# hardcoded around (reference main.py:144-148,161-184: obs['observation']
# indexing, done from info['is_success'], env.compute_reward relabeling).
# HER convention: n-step 1 (relabeled returns are recomputed per goal, so
# n-step bootstrapping over relabeled rewards needs per-step recompute —
# the reference relabels single transitions too, main.py:161-184).
# Usage: bash runs/fetch_supervisor.sh ENV DIR [TOTAL_STEPS] [EXTRA...]
#   e.g. bash runs/fetch_supervisor.sh FetchReach-v4 runs/fetchreach_her_tpu 30000
#        bash runs/fetch_supervisor.sh FetchPush-v4 runs/fetchpush_her_tpu 300000
#        bash runs/fetch_supervisor.sh FetchPush-v4 runs/fetchpush_noher_tpu 300000 --no-her
ENV_ID=${1:?usage: fetch_supervisor.sh ENV DIR [TOTAL] [extra flags...]}
DIR=${2:?usage: fetch_supervisor.sh ENV DIR [TOTAL] [extra flags...]}
TOTAL=${3:-30000}
shift 3 2>/dev/null || shift 2
HER_FLAG="--her"
EXTRA=()
for a in "$@"; do
  if [ "$a" = "--no-her" ]; then HER_FLAG=""; else EXTRA+=("$a"); fi
done
while :; do
  STEP=$(ls "$DIR/checkpoints" 2>/dev/null | grep -E '^[0-9]+$' | sort -n | tail -1)
  STEP=${STEP:-0}
  REM=$((TOTAL - STEP))
  if [ "$REM" -le 0 ]; then echo "supervisor: done at step $STEP"; break; fi
  echo "supervisor: leg from step $STEP, $REM to go"
  # --random-eps/--action-l2: the HER-DDPG exploration mixture + action
  # regularizer (Andrychowicz et al. 2017 §4.4). Measured necessary round
  # 5: without them FetchReach's actor collapses to a saturated tanh
  # corner (constant [-1,1,-1,-1], success pinned ~5%).
  python train.py --env "$ENV_ID" $HER_FLAG --n-step 1 --num-envs 8 \
    --async-collect --total-steps "$REM" --warmup 1000 \
    --lr-actor 1e-3 --lr-critic 1e-3 \
    --random-eps 0.3 --action-l2 1.0 \
    --eval-interval 2000 --eval-episodes 20 \
    --checkpoint-interval 10000 --snapshot-replay --resume \
    --max-rss-gb 80 --log-dir "$DIR" "${EXTRA[@]}"
  RC=$?
  if [ "$RC" -ne 75 ] && [ "$RC" -ne 0 ]; then
    echo "supervisor: leg failed rc=$RC"; exit "$RC"
  fi
done
