#!/bin/bash
# Supervised REAL-MuJoCo training legs — the halfcheetah_tpu_r2 recipe
# (8-actor async pool, CPU-jitted acting, K=32 fused dispatch, async PER
# write-back, exit-75 RSS self-preemption) pointed at any gymnasium env.
# Single critic by default: the round-4 Hopper comparison showed clipped
# double-Q's pessimism suppresses the optimistic Q that discovers hop/
# gait cycles on real contacts (twin best 1,030 vs single 3,558 —
# runs/hopper_mujoco_tpu/NOTES.md). Pass --twin-critic via EXTRA args
# for the ablation arm.
# Usage: bash runs/mujoco_supervisor.sh ENV DIR [TOTAL_STEPS] [EXTRA...]
#   e.g. bash runs/mujoco_supervisor.sh Hopper-v5 runs/hopper_mujoco_tpu
ENV_ID=${1:?usage: mujoco_supervisor.sh ENV DIR [TOTAL] [extra flags...]}
DIR=${2:?usage: mujoco_supervisor.sh ENV DIR [TOTAL] [extra flags...]}
TOTAL=${3:-2000000}
shift 3 2>/dev/null || shift 2
while :; do
  STEP=$(ls "$DIR/checkpoints" 2>/dev/null | grep -E '^[0-9]+$' | sort -n | tail -1)
  STEP=${STEP:-0}
  REM=$((TOTAL - STEP))
  if [ "$REM" -le 0 ]; then echo "supervisor: done at step $STEP"; break; fi
  echo "supervisor: leg from step $STEP, $REM to go"
  python train.py --env "$ENV_ID" --num-envs 8 --async-collect \
    --async-writeback --steps-per-dispatch 32 --n-step 3 \
    --noise-decay-steps 1000000 --noise-scale-final 0.15 \
    --total-steps "$REM" --eval-interval 10000 \
    --eval-episodes 5 --checkpoint-interval 100000 --snapshot-replay \
    --resume --max-rss-gb 80 --log-dir "$DIR" "$@"
  RC=$?
  # 75 = watchdog preemption (checkpointed; go again); 0 = leg budget done
  if [ "$RC" -ne 75 ] && [ "$RC" -ne 0 ]; then
    echo "supervisor: leg failed rc=$RC"; exit "$RC"
  fi
done
