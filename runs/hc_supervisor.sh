#!/bin/bash
# Supervised HalfCheetah legs: each leg resumes from the newest checkpoint
# and self-preempts via --max-rss-gb before the host OOM killer would act
# (the tunnel client leaks every host->device transfer; docs/REMOTE_TPU.md).
TOTAL=6000000
DIR=runs/halfcheetah_tpu_r2
while :; do
  STEP=$(ls "$DIR/checkpoints" 2>/dev/null | grep -E '^[0-9]+$' | sort -n | tail -1)
  STEP=${STEP:-0}
  REM=$((TOTAL - STEP))
  if [ "$REM" -le 0 ]; then echo "supervisor: done at step $STEP"; break; fi
  echo "supervisor: leg from step $STEP, $REM to go"
  python train.py --env HalfCheetah-v5 --num-envs 8 --async-collect \
    --async-writeback --steps-per-dispatch 32 --n-step 5 \
    --v-min -100 --v-max 1500 --noise-decay-steps 2000000 \
    --noise-scale-final 0.15 --total-steps "$REM" --eval-interval 20000 \
    --eval-episodes 5 --checkpoint-interval 100000 --snapshot-replay \
    --resume --max-rss-gb 80 --log-dir "$DIR"
  RC=$?
  # 75 = watchdog preemption (checkpointed; go again); 0 = leg budget done
  if [ "$RC" -ne 75 ] && [ "$RC" -ne 0 ]; then
    echo "supervisor: leg failed rc=$RC"; exit "$RC"
  fi
done
